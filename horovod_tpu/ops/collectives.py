"""In-jit (traced) collective implementations: the XLA/ICI data plane.

This is the TPU-native replacement for the reference's NCCL ops layer
(horovod/common/ops/nccl_operations.cc — NCCLAllreduce/NCCLAllgather/
NCCLBroadcast/NCCLAlltoall; SURVEY.md §2.2): where NCCL launches ring
kernels on a CUDA stream, here each collective is a ``jax.lax`` primitive
over a named mesh axis that XLA lowers onto ICI — fusion, overlap, and
scheduling come from the compiler rather than hand-managed streams.

These functions are called by ``horovod_tpu.mpi_ops`` when the input is a
JAX tracer (i.e. inside ``jit``/``shard_map``), and may also be used
directly in SPMD training code.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..wire import ReduceOp

AxisName = Union[str, Sequence[str]]


def axis_size(axis_name: AxisName) -> int:
    try:
        return lax.axis_size(axis_name)
    except AttributeError:  # jax < 0.5: psum of a literal 1 is the idiom
        return lax.psum(1, axis_name)


def shard_map(*args, **kwargs):
    """Version-portable ``jax.shard_map``.

    Bridges two renames: the import moved from
    ``jax.experimental.shard_map`` to top-level ``jax``, and the
    replication-check kwarg flipped ``check_rep`` -> ``check_vma``.
    Callers may pass either kwarg; whichever the installed jax rejects is
    translated to the one it accepts.
    """
    try:
        from jax import shard_map as sm
    except ImportError:  # pre-top-level layout
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(*args, **kwargs)
    except TypeError:
        swaps = {"check_vma": "check_rep", "check_rep": "check_vma"}
        for old, new in swaps.items():
            if old in kwargs and new not in kwargs:
                kwargs = dict(kwargs)
                kwargs[new] = kwargs.pop(old)
                return sm(*args, **kwargs)
        raise


def _axes_tuple(axis_name: AxisName):
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def ensure_varying(x, axis_name: AxisName):
    """Cast ``x`` to 'varying' over every requested axis (shard_map vma).

    Classic collective semantics treat the input as this shard's value;
    psum of a replicated value multiplies by the axis size, pmean is the
    identity.  JAX's vma typing instead *rejects* collectives over axes the
    value is invariant on — this cast restores the classic behavior at the
    public API boundary.  (Gradient reduction wants different semantics for
    invariant leaves — see optimizer._tree_allreduce.)
    """
    try:
        vma = jax.typeof(x).vma
    except Exception:
        return x
    missing = tuple(a for a in _axes_tuple(axis_name) if a not in vma)
    return lax.pcast(x, missing, to="varying") if missing else x


class _Subset:
    """Static geometry of a rank subset over ONE mesh axis — the traced
    process-set bridge (reference: process_set.cc communicator subsetting;
    SURVEY.md §2.1).

    XLA exposes no subgroup collectives through shard_map in current jax
    (``axis_index_groups`` raises NotImplementedError), so subset
    collectives lower onto FULL-axis collectives with identity-masked
    contributions — on ICI the full-axis psum is bandwidth-optimal anyway,
    and every rank of the mesh executes the same SPMD program as shard_map
    requires.  Semantics: member ranks get the set's result; non-member
    ranks pass through unchanged where shapes allow (allreduce, broadcast,
    alltoall), keep their own leading s0/k chunk where the output shape
    shrinks (reducescatter), and receive the set's result where it must be
    uniform (allgather).
    """

    def __init__(self, axis_name: AxisName, member_ranks: Sequence[int]):
        if not isinstance(axis_name, str):
            raise ValueError(
                "process_set collectives run over a single mesh axis; got "
                f"axis_name={axis_name!r}")
        self.axis = axis_name
        self.n = axis_size(axis_name)
        self.members = sorted(set(int(r) for r in member_ranks))
        if not self.members:
            raise ValueError("process set has no members")
        if self.members[0] < 0 or self.members[-1] >= self.n:
            raise ValueError(
                f"process set ranks {self.members} out of range for axis "
                f"{axis_name!r} of size {self.n} (ranks map to axis indices)")
        self.k = len(self.members)
        idx = lax.axis_index(axis_name)
        mset = set(self.members)
        self.is_member = jnp.asarray(
            [i in mset for i in range(self.n)])[idx]
        # Position of this rank within the set (0 for non-members — only
        # ever used behind an is_member select).
        self.pos = jnp.asarray(
            [self.members.index(i) if i in mset else 0
             for i in range(self.n)])[idx]

    def masked(self, x, identity):
        """This rank's contribution: x for members, the op identity else."""
        return jnp.where(self.is_member, x, identity)

    def passthrough(self, result, x):
        """Set result for members; x unchanged for non-members."""
        return jnp.where(self.is_member, result, x)


def allreduce(x, axis_name: AxisName, op: ReduceOp = ReduceOp.AVERAGE,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              member_ranks: Optional[Sequence[int]] = None):
    x = ensure_varying(x, axis_name)
    if member_ranks is not None:
        # Scales apply to the set's result only; non-members pass through
        # UNCHANGED (the documented subset semantics).
        return _subset_allreduce(x, axis_name, op, member_ranks,
                                 prescale_factor, postscale_factor)
    if (op in (ReduceOp.SUM, ReduceOp.AVERAGE)
            and prescale_factor == 1.0 and postscale_factor == 1.0):
        # Device-plane codec auto-dispatch (HOROVOD_WIRE_COMPRESSION
        # device=int8|int4|int8g): eligible fp32 payloads ride the
        # block-scaled ring under the configured schedule; everything else
        # falls through bit-identically.  No recursion:
        # quantized_allreduce only calls back here when the same
        # eligibility test fails.
        codec, min_bytes = _device_codec_defaults()
        if _codec_enabled(codec):
            axes = ((axis_name,) if isinstance(axis_name, str)
                    else tuple(axis_name))
            if len(axes) == 1 and quantized_allreduce_eligible(
                    x, axis_size(axes[0]), min_bytes):
                return quantized_allreduce(x, axes[0], op=op,
                                           min_bytes=min_bytes,
                                           codec=codec)
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    if op == ReduceOp.AVERAGE:
        out = lax.pmean(x, axis_name)
    elif op == ReduceOp.SUM:
        out = lax.psum(x, axis_name)
    elif op == ReduceOp.MIN:
        out = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        out = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        out = jnp.prod(lax.all_gather(x, axis_name, axis=0), axis=0)
    elif op == ReduceOp.ADASUM:
        out = adasum(x, axis_name)
    else:
        raise ValueError(f"unsupported reduce op {op}")
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return out


def _reduce_identity(x, op: ReduceOp):
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
        return jnp.zeros_like(x)
    if op == ReduceOp.PRODUCT:
        return jnp.ones_like(x)
    if x.dtype == jnp.bool_:
        # bool Min == AND (identity True), bool Max == OR (identity False)
        if op == ReduceOp.MIN:
            return jnp.ones_like(x)
        if op == ReduceOp.MAX:
            return jnp.zeros_like(x)
        raise ValueError(f"unsupported reduce op {op}")
    info = (jnp.finfo if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo)(x.dtype)
    if op == ReduceOp.MIN:
        return jnp.full_like(x, info.max)
    if op == ReduceOp.MAX:
        return jnp.full_like(x, info.min)
    raise ValueError(f"unsupported reduce op {op}")


def _subset_allreduce(x, axis_name: str, op: ReduceOp,
                      member_ranks: Sequence[int],
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0):
    sub = _Subset(axis_name, member_ranks)
    xs = x
    if prescale_factor != 1.0:
        xs = xs * jnp.asarray(prescale_factor, dtype=x.dtype)
    if op == ReduceOp.ADASUM:
        out = adasum(xs, axis_name, member_ranks=sub.members)
    else:
        contrib = sub.masked(xs, _reduce_identity(xs, op))
        if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
            out = lax.psum(contrib, axis_name)
            if op == ReduceOp.AVERAGE:
                out = out / sub.k
        elif op == ReduceOp.MIN:
            out = lax.pmin(contrib, axis_name)
        elif op == ReduceOp.MAX:
            out = lax.pmax(contrib, axis_name)
        elif op == ReduceOp.PRODUCT:
            out = jnp.prod(lax.all_gather(contrib, axis_name, axis=0),
                           axis=0)
        else:
            raise ValueError(f"unsupported reduce op {op}")
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return sub.passthrough(out, x)


def allgather(x, axis_name: AxisName,
              member_ranks: Optional[Sequence[int]] = None):
    """Concatenate along dim 0 across the axis (Horovod allgather semantics).

    With ``member_ranks``, only the members' shards are concatenated (in
    set order); every rank of the mesh receives that concatenation (the
    output shape must be uniform across the SPMD program)."""
    x = ensure_varying(x, axis_name)
    if member_ranks is None:
        codec, min_bytes = _device_codec_defaults()
        if (_codec_enabled(codec) and isinstance(axis_name, str)
                and getattr(x, "ndim", 0) >= 1
                and quantized_collective_eligible(
                    x, axis_size(axis_name), min_bytes)):
            return quantized_allgather(x, axis_name, min_bytes=min_bytes,
                                       codec=codec)
        return lax.all_gather(x, axis_name, axis=0, tiled=True)
    sub = _Subset(axis_name, member_ranks)
    # One full-axis psum of a [k, s0, ...] buffer in which each member
    # deposits its own shard at its set position (non-members contribute
    # zeros): the rows are disjoint, so the sum IS the member concatenation.
    # Memory and wire bytes are O(k*s0) — not the O(n*s0) of the previous
    # full-axis all_gather + row select, an n/k blowup exactly when the set
    # is small relative to the mesh — and psum's vma semantics make the
    # result axis-invariant (replicated), so out_specs expecting
    # replication keep working.
    row = sub.masked(x, jnp.zeros_like(x))
    # psum converts bool inputs to integers; round-trip through int32 so
    # the output dtype matches the input (as the reference's allgather does).
    calc_dtype = jnp.int32 if x.dtype == jnp.bool_ else x.dtype
    contrib = jnp.zeros((sub.k,) + x.shape, calc_dtype)
    contrib = lax.dynamic_update_slice(
        contrib, row[None].astype(calc_dtype), (sub.pos,) + (0,) * x.ndim)
    full = lax.psum(contrib, axis_name)                # [k, s0, ...]
    return full.reshape(
        (sub.k * x.shape[0],) + x.shape[1:]).astype(x.dtype)


def broadcast(x, root_rank: int, axis_name: AxisName,
              member_ranks: Optional[Sequence[int]] = None):
    """Every member receives root's value (``root_rank`` is the GLOBAL
    rank / axis index, as in the reference's process-set broadcast —
    socket_controller.cc resolves it within the member list).

    Implemented as a masked psum — one collective, no gather of the full
    axis — which XLA lowers to an ICI broadcast-like pattern.
    """
    x = ensure_varying(x, axis_name)
    if member_ranks is None:
        codec, min_bytes = _device_codec_defaults()
        if (_codec_enabled(codec) and isinstance(axis_name, str)
                and quantized_collective_eligible(
                    x, axis_size(axis_name), min_bytes)):
            return quantized_broadcast(x, root_rank, axis_name,
                                       min_bytes=min_bytes, codec=codec)
    idx = lax.axis_index(axis_name)
    sub = None
    if member_ranks is not None:
        sub = _Subset(axis_name, member_ranks)
        if int(root_rank) not in sub.members:
            raise ValueError(
                f"broadcast root {root_rank} is not in the process set "
                f"{sub.members}")
    # where() (not multiply-by-mask) so NaN/Inf in non-root shards are
    # discarded rather than propagated through the sum.
    contribution = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    out = lax.psum(contribution, axis_name)
    return out if sub is None else sub.passthrough(out, x)


def alltoall(x, axis_name: AxisName,
             member_ranks: Optional[Sequence[int]] = None):
    """Equal-splits alltoall: first dim is split across the axis and the
    received chunks are concatenated along dim 0 (lax.all_to_all).

    With ``member_ranks``, dim 0 is split |set| ways and exchanged among
    the members only; non-members pass through unchanged."""
    x = ensure_varying(x, axis_name)
    if member_ranks is None:
        codec, min_bytes = _device_codec_defaults()
        if (_codec_enabled(codec) and isinstance(axis_name, str)
                and getattr(x, "ndim", 0) >= 1
                and quantized_collective_eligible(
                    x, axis_size(axis_name), min_bytes,
                    divisor=axis_size(axis_name))):
            return quantized_alltoall(x, axis_name, min_bytes=min_bytes,
                                      codec=codec)
        return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
    sub = _Subset(axis_name, member_ranks)
    s0 = x.shape[0]
    if s0 % sub.k:
        raise ValueError(
            f"alltoall dim 0 ({s0}) must divide by the process-set size "
            f"({sub.k})")
    c = s0 // sub.k
    n = sub.n
    # k ppermute rounds of one [c, ...] chunk each — total bytes moved
    # equal the baseline alltoall (a full-axis all_gather here would be an
    # n-times memory blowup).  In round t, the member at set position p
    # sends its chunk (p+t)%k to the member at position (p+t)%k, who
    # stores it at slot p = (recv_pos - t) % k.  Non-members self-send
    # and are patched through at the end.
    out = jnp.zeros_like(x)
    for t in range(sub.k):
        send_start = ((sub.pos + t) % sub.k) * c
        chunk = lax.dynamic_slice_in_dim(x, send_start, c, axis=0)
        if t == 0:
            moved = chunk
        else:
            pair = {sub.members[p]: sub.members[(p + t) % sub.k]
                    for p in range(sub.k)}
            perm = [(i, pair.get(i, i)) for i in range(n)]
            moved = lax.ppermute(chunk, axis_name, perm)
        recv_start = ((sub.pos - t) % sub.k) * c
        out = lax.dynamic_update_slice_in_dim(out, moved, recv_start,
                                              axis=0)
    return sub.passthrough(out, x)


def reducescatter(x, axis_name: AxisName, op: ReduceOp = ReduceOp.SUM,
                  prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                  member_ranks: Optional[Sequence[int]] = None):
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("in-jit reducescatter supports Sum and Average")
    x = ensure_varying(x, axis_name)
    if member_ranks is not None:
        sub = _Subset(axis_name, member_ranks)
        s0 = x.shape[0]
        if s0 % sub.k:
            raise ValueError(
                f"reducescatter dim 0 ({s0}) must divide by the "
                f"process-set size ({sub.k})")
        c = s0 // sub.k
        xs = x
        if prescale_factor != 1.0:
            xs = xs * jnp.asarray(prescale_factor, dtype=x.dtype)
        summed = lax.psum(sub.masked(xs, jnp.zeros_like(xs)), axis_name)
        out = lax.dynamic_slice_in_dim(summed, sub.pos * c, c, axis=0)
        if op == ReduceOp.AVERAGE:
            out = out / sub.k
        if postscale_factor != 1.0:
            out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
        # Non-members keep their own leading chunk UNSCALED (shape-uniform
        # pass-through analog).
        return jnp.where(sub.is_member, out,
                         lax.slice_in_dim(x, 0, c, axis=0))
    if prescale_factor == 1.0 and postscale_factor == 1.0:
        codec, min_bytes = _device_codec_defaults()
        if (_codec_enabled(codec) and isinstance(axis_name, str)
                and getattr(x, "ndim", 0) >= 1
                and quantized_collective_eligible(
                    x, axis_size(axis_name), min_bytes,
                    divisor=axis_size(axis_name))):
            return quantized_reducescatter(x, axis_name, op=op,
                                           min_bytes=min_bytes, codec=codec)
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    out = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    if op == ReduceOp.AVERAGE:
        out = out / axis_size(axis_name)
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return out


def adasum(x, axis_name: AxisName,
           member_ranks: Optional[Sequence[int]] = None):
    """Adasum scale-invariant reduction over a mesh axis.

    TPU-native version of the reference's recursive vector-halving/distance-
    doubling Adasum (horovod/common/ops/adasum/adasum.h; SURVEY.md §2.2):
    log2(n) rounds of pairwise combination, each round exchanging partners
    via ``ppermute`` over ICI.  For a pair (a, b):

        adasum(a, b) = (1 - a.b / (2|a|^2)) a + (1 - a.b / (2|b|^2)) b

    Requires the axis size to be a power of two (as the reference does for
    its pure Adasum path).  With ``member_ranks`` the pairwise rounds run
    among the members only (|set| must be a power of two); non-members
    ppermute to themselves, and adasum(a, a) = a leaves them unchanged.
    """
    n = axis_size(axis_name)
    if member_ranks is not None:
        members = sorted(set(int(r) for r in member_ranks))
    else:
        members = list(range(n))
    m = len(members)
    if m & (m - 1) != 0:
        raise ValueError(f"Adasum requires a power-of-two size, got {m}")
    rounds = m.bit_length() - 1
    out = x
    for k in range(rounds):
        stride = 1 << k
        # Pair set-positions p <-> p^stride, mapped back to global axis
        # indices; everyone else exchanges with itself.
        pair = {members[p]: members[p ^ stride] for p in range(m)}
        perm = [(i, pair.get(i, i)) for i in range(n)]
        other = lax.ppermute(out, axis_name, perm)
        a, b = out, other
        dot = jnp.vdot(a, b).astype(jnp.float32)
        na = jnp.vdot(a, a).astype(jnp.float32)
        nb = jnp.vdot(b, b).astype(jnp.float32)
        eps = jnp.asarray(1e-30, jnp.float32)
        ca = (1.0 - dot / (2.0 * jnp.maximum(na, eps))).astype(x.dtype)
        cb = (1.0 - dot / (2.0 * jnp.maximum(nb, eps))).astype(x.dtype)
        combined = ca * a + cb * b
        # Both members of a pair compute the same combined vector (the
        # formula is symmetric), so no extra exchange is needed.
        out = combined
    return out


def barrier(axis_name: AxisName):
    """A collective no-op that forces synchronisation across the axis."""
    token = jnp.zeros((), dtype=jnp.float32)
    return lax.psum(token, axis_name)


# --- Quantized (block-scaled) collectives ----------------------------------

def _device_codec_defaults():
    """(codec, min_bytes) from the live context when initialized, else from
    the environment — trace-time only, never inside the compiled program."""
    try:
        from ..context import HorovodContext
        if HorovodContext.initialized():
            cfg = HorovodContext.instance().cfg
            return (getattr(cfg, "wire_compression_device", "none"),
                    getattr(cfg, "wire_compression_min_bytes", 1 << 16))
    except Exception:
        pass
    from ..utils.env import get_int, get_wire_compression_planes
    return (get_wire_compression_planes()[1],
            get_int("HOROVOD_WIRE_COMPRESSION_MIN_BYTES", 1 << 16))


def _device_schedule_default() -> str:
    """Configured ring schedule (HOROVOD_DEVICE_SCHEDULE / context cfg),
    unresolved — :func:`resolve_device_schedule` turns 'auto' into a
    concrete schedule for a given world size."""
    try:
        from ..context import HorovodContext
        if HorovodContext.initialized():
            return getattr(HorovodContext.instance().cfg,
                           "device_schedule", "auto")
    except Exception:
        pass
    from ..utils.env import get_device_schedule
    return get_device_schedule()


def _codec_enabled(codec: str) -> bool:
    from . import quantize as qz
    return codec != "none" and codec in qz.DEVICE_WIRE_CODECS


def resolve_device_schedule(world: int, schedule: Optional[str] = None) -> str:
    """Resolve a schedule request to a concrete {ring, bidi, torus} for a
    ``world``-rank axis.  ``None`` reads the configured default.

    - ``torus`` demotes to ``bidi`` when ``world`` has no 2-D
      factorization (prime or < 4) — deterministic, never an error;
    - ``auto`` selects from the mesh shape: ``torus`` when a near-square
      factorization with major axis >= 4 exists (pod-slice shapes, where
      O(a+b) chunk-hops beat the 1-D ring's O(n)), ``bidi`` for rings of
      4+ (both ICI directions carry half chunks), plain ``ring``
      otherwise.
    """
    from . import quantize as qz

    if schedule is None:
        schedule = _device_schedule_default()
    s = (schedule or "auto").lower()
    f = qz.torus_factors(world)
    if s == "torus" and f is None:
        s = "bidi"
    if s == "auto":
        if f is not None and f[0] >= 4:
            s = "torus"
        elif world >= 4:
            s = "bidi"
        else:
            s = "ring"
    if s not in ("ring", "bidi", "torus"):
        s = "ring"
    return s


def quantized_collective_eligible(x, world: int, min_bytes: int,
                                  divisor: int = 1) -> bool:
    """Shared demotion rule for every device-plane quantized collective,
    used by the traced path, the optimizer's error-feedback gate, and the
    eager device plane so every layer falls the same way: fp32 only
    (quantizing low-precision or integer payloads either loses exactness
    or gains nothing), at least ``min_bytes`` of payload (small tensors
    are latency-bound and the per-block scale overhead erodes the ratio),
    and a real ring to run on.  ``divisor`` adds the leading-dim
    divisibility requirement of reducescatter/alltoall.
    """
    dtype = getattr(x, "dtype", None)
    shape = tuple(getattr(x, "shape", ()))
    size = 1
    for d in shape:  # static under jit
        size *= int(d)
    if divisor > 1 and (not shape or int(shape[0]) % int(divisor)):
        return False
    return (world > 1 and dtype == jnp.float32
            and size * 4 >= int(min_bytes))


def quantized_allreduce_eligible(x, world: int, min_bytes: int) -> bool:
    """Allreduce instance of :func:`quantized_collective_eligible` (kept
    as its own name — the optimizer and device plane import it)."""
    return quantized_collective_eligible(x, world, min_bytes)


def _tree_permute(payload, axis_name: str, perm):
    """ppermute every leaf of a (codes, scales) payload pytree — scales
    may be a nested (sub, group) pair for the int8g codec."""
    return jax.tree_util.tree_map(
        lambda a: lax.ppermute(a, axis_name, perm), payload)


def _ring_reduce_scatter(chunks, axis_name: str, size: int, pos, off: int,
                         d: int, perm, codec: str,
                         interpret: Optional[bool]):
    """Generic quantized ring reduce-scatter over one logical ring.

    ``chunks`` is [size, c] fp32; ``pos`` is this rank's (traced) position
    on the ring; ``perm`` is the ppermute pattern realizing pos -> pos+d.
    The rank at position p starts the partial for row (p + off) % size and
    adds row (p + off - d*t) % size at hop t; after size-1 hops the
    fully-summed row (p + off + d) % size lands on position p.  Each hop
    quantizes the running partial (cpp/wire_codec.h semantics exactly),
    moves codes + scales, and accumulates in fp32 against the receiver's
    own contribution — the ring never adds quantized values together.
    """
    from . import quantize as qz

    c = chunks.shape[1]
    acc = lax.dynamic_index_in_dim(chunks, jnp.mod(pos + off, size), 0,
                                   keepdims=False)
    for t in range(size - 1):
        payload = qz.quantize(acc, codec, interpret)
        payload = _tree_permute(payload, axis_name, perm)
        own = lax.dynamic_index_in_dim(
            chunks, jnp.mod(pos + off - d * (t + 1), size), 0,
            keepdims=False)
        acc = qz.dequantize(payload[0], payload[1], c, codec,
                            interpret) + own
    return acc


def _ring_all_gather(payload, axis_name: str, size: int, pos,
                     owned_off: int, d: int, perm, chunk: int, codec: str,
                     interpret: Optional[bool]):
    """Gather phase: the position-p rank owns the fully-summed row
    (p + owned_off) % size, already ENCODED in ``payload``; encodings are
    forwarded verbatim around the ring, so every rank dequantizes
    identical bytes — the result is bit-identical across ranks (the same
    verbatim-forwarding rule the host codec uses).  Returns [size, chunk]
    fp32."""
    from . import quantize as qz

    out = ensure_varying(jnp.zeros((size, chunk), jnp.float32), axis_name)
    cur = payload
    for t in range(size):
        piece = qz.dequantize(cur[0], cur[1], chunk, codec, interpret)
        out = lax.dynamic_update_index_in_dim(
            out, piece, jnp.mod(pos - d * t + owned_off, size), 0)
        if t < size - 1:
            cur = _tree_permute(cur, axis_name, perm)
    return out


def _ring_all_gather_payload(payload, axis_name: str, size: int, pos,
                             owned_off: int, d: int, perm):
    """Gather ENCODED payloads without decoding: every leaf gains a
    leading ``size`` dim where slot s holds the encoding of ring row s
    (the position-p rank owns row (p + owned_off) % size).  Used by the
    torus schedule to forward stage-2 encodings verbatim through the
    stage-1 gather."""
    def init(leaf):
        return ensure_varying(
            jnp.zeros((size,) + leaf.shape, leaf.dtype), axis_name)

    out = jax.tree_util.tree_map(init, payload)
    cur = payload
    for t in range(size):
        slot = jnp.mod(pos - d * t + owned_off, size)
        out = jax.tree_util.tree_map(
            lambda o, l: lax.dynamic_update_index_in_dim(o, l, slot, 0),
            out, cur)
        if t < size - 1:
            cur = _tree_permute(cur, axis_name, perm)
    return out


def _ring_allreduce_sum(flat, axis_name: str, codec: str,
                        interpret: Optional[bool]):
    """Unidirectional ring: reduce-scatter then all-gather, world-1
    ``ppermute`` hops each, one chunk of ceil(len/world) per hop."""
    from . import quantize as qz

    n = axis_size(axis_name)
    length = flat.shape[0]
    chunk = -(-length // n)
    x = (jnp.pad(flat, (0, n * chunk - length))
         if n * chunk != length else flat)
    chunks = x.reshape(n, chunk)
    me = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    acc = _ring_reduce_scatter(chunks, axis_name, n, me, 0, +1, perm,
                               codec, interpret)
    payload = qz.quantize(acc, codec, interpret)
    out = _ring_all_gather(payload, axis_name, n, me, +1, +1, perm, chunk,
                           codec, interpret)
    return out.reshape(-1)[:length]


def _bidi_ring_allreduce_sum(flat, axis_name: str, codec: str,
                             interpret: Optional[bool]):
    """Bidirectional ring: each chunk splits into a front half riding the
    forward ring and a back half riding the backward ring, so both ICI
    directions of the torus link carry half the bytes per hop
    concurrently (the two streams are data-independent, letting XLA
    overlap them).  Same hop count and per-rank byte totals as the
    unidirectional ring; per-link-direction bytes halve."""
    from . import quantize as qz

    n = axis_size(axis_name)
    length = flat.shape[0]
    chunk = -(-length // n)
    x = (jnp.pad(flat, (0, n * chunk - length))
         if n * chunk != length else flat)
    chunks = x.reshape(n, chunk)
    front = chunk // 2
    me = lax.axis_index(axis_name)
    perm_f = [(i, (i + 1) % n) for i in range(n)]
    perm_b = [(i, (i - 1) % n) for i in range(n)]
    acc_f = _ring_reduce_scatter(chunks[:, :front], axis_name, n, me, 0,
                                 +1, perm_f, codec, interpret)
    acc_b = _ring_reduce_scatter(chunks[:, front:], axis_name, n, me, 0,
                                 -1, perm_b, codec, interpret)
    pf = qz.quantize(acc_f, codec, interpret)
    pb = qz.quantize(acc_b, codec, interpret)
    out_f = _ring_all_gather(pf, axis_name, n, me, +1, +1, perm_f, front,
                             codec, interpret)
    out_b = _ring_all_gather(pb, axis_name, n, me, -1, -1, perm_b,
                             chunk - front, codec, interpret)
    out = jnp.concatenate([out_f, out_b], axis=1)
    return out.reshape(-1)[:length]


def _torus_allreduce_sum(flat, axis_name: str, a: int, b: int, codec: str,
                         interpret: Optional[bool]):
    """2-D torus decomposition over an a x b logical mesh (rank = i*b + j;
    a = major axis, b = minor axis): reduce-scatter along the minor axis
    (rings of size b within each major row), then along the major axis
    (rings of size a within each column), gather in reverse.  O(a+b)
    chunk-hops instead of the 1-D ring's O(ab), per the MLPerf TPU-pod
    schedule.

    Quantization points: every reduce-scatter hop re-encodes its running
    fp32 partial (stage 1 on ceil(len/b) chunks, stage 2 on
    ceil(len/(a*b))-ish sub-chunks); the globally-summed sub-chunk is
    then encoded ONCE and both gather phases forward that encoding
    verbatim — the stage-1 gather moves the stacked stage-2 payloads as
    opaque bytes — so every rank decodes identical bytes and the result
    is bit-identical across all a*b ranks."""
    from . import quantize as qz

    n = a * b
    length = flat.shape[0]
    me = lax.axis_index(axis_name)
    row_pos = jnp.mod(me, b)       # position on the minor-axis ring (j)
    col_pos = me // b              # position on the major-axis ring (i)
    c1 = -(-length // b)
    x = (jnp.pad(flat, (0, b * c1 - length))
         if b * c1 != length else flat)
    rows = x.reshape(b, c1)
    perm_row = [(g, (g // b) * b + ((g % b) + 1) % b) for g in range(n)]
    perm_col = [(g, ((g // b + 1) % a) * b + (g % b)) for g in range(n)]

    # Stage 1: minor-axis reduce-scatter; rank (i, j) ends with minor
    # chunk (j+1) % b summed over its major row.
    acc1 = _ring_reduce_scatter(rows, axis_name, b, row_pos, 0, +1,
                                perm_row, codec, interpret)
    # Stage 2: major-axis reduce-scatter of that chunk; rank (i, j) ends
    # with sub-chunk (i+1) % a of minor chunk (j+1) % b, globally summed.
    c2 = -(-c1 // a)
    y = jnp.pad(acc1, (0, a * c2 - c1)) if a * c2 != c1 else acc1
    sub_rows = y.reshape(a, c2)
    acc2 = _ring_reduce_scatter(sub_rows, axis_name, a, col_pos, 0, +1,
                                perm_col, codec, interpret)

    # Gather in reverse, forwarding encodings verbatim.
    payload2 = qz.quantize(acc2, codec, interpret)
    stacked2 = _ring_all_gather_payload(payload2, axis_name, a, col_pos,
                                        +1, +1, perm_col)
    stacked1 = _ring_all_gather_payload(stacked2, axis_name, b, row_pos,
                                        +1, +1, perm_row)
    # stacked1 leaves are [b, a, ...]: slot (m, s) = the encoding of
    # sub-chunk s of minor chunk m.
    pieces = []
    for m in range(b):
        for s in range(a):
            leaf = jax.tree_util.tree_map(
                lambda l, _m=m, _s=s: l[_m, _s], stacked1)
            pieces.append(qz.dequantize(leaf[0], leaf[1], c2, codec,
                                        interpret))
    out = jnp.stack(pieces).reshape(b, a * c2)[:, :c1]
    return out.reshape(-1)[:length]


def _quantized_ring_allreduce_sum(flat, axis_name: str,
                                  interpret: Optional[bool] = None,
                                  codec: str = "int8",
                                  schedule: str = "ring"):
    """Block-scaled allreduce of a flat fp32 vector over ONE mesh axis
    (the traced mirror of the host ring's wire codecs), dispatching on
    ``schedule`` — 'ring' (unidirectional), 'bidi', or 'torus'.  Demotes
    deterministically: torus -> bidi when the world has no 2-D
    factorization, bidi -> ring when chunks are too short to split
    (mirrored by quantize.ring_bytes so byte accounting stays exact)."""
    from . import quantize as qz

    n = axis_size(axis_name)
    if schedule == "torus":
        f = qz.torus_factors(n)
        if f is None:
            schedule = "bidi"
        else:
            return _torus_allreduce_sum(flat, axis_name, f[0], f[1],
                                        codec, interpret)
    chunk = -(-flat.shape[0] // n)
    if schedule == "bidi" and chunk >= 2:
        return _bidi_ring_allreduce_sum(flat, axis_name, codec, interpret)
    return _ring_allreduce_sum(flat, axis_name, codec, interpret)


def quantized_allreduce(x, axis_name: AxisName,
                        op: ReduceOp = ReduceOp.SUM,
                        min_bytes: Optional[int] = None,
                        codec: Optional[str] = None,
                        schedule: Optional[str] = None,
                        interpret: Optional[bool] = None):
    """Allreduce through the block-scaled ring when ``x`` is eligible;
    otherwise demotes to the plain (uncompressed) collective, bit-identical
    to :func:`allreduce`.

    ``min_bytes=None`` reads HOROVOD_WIRE_COMPRESSION_MIN_BYTES (context
    config when initialized); ``codec=None`` reads the configured device
    codec (falling back to int8 when the config says none — an explicit
    call asks for quantization); ``schedule=None`` reads
    HOROVOD_DEVICE_SCHEDULE and resolves 'auto' from the axis size.  Byte
    accounting (``data_plane_stats()['device_raw'/'device_encoded']``) is
    recorded per trace — under ``jax.jit`` cache reuse the program moves
    the same bytes every call, so the per-trace note is the per-call wire
    cost.
    """
    from . import quantize as qz

    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"quantized_allreduce supports Sum and Average, got {op}")
    if min_bytes is None:
        min_bytes = _device_codec_defaults()[1]
    axes = _axes_tuple(axis_name)
    world = 1
    for a in axes:
        world *= axis_size(a)
    if (len(axes) != 1
            or not quantized_allreduce_eligible(x, world, min_bytes)):
        return allreduce(x, axis_name, op=op)
    if codec is None:
        codec = _device_codec_defaults()[0]
    if not _codec_enabled(codec):
        codec = "int8"
    sched = resolve_device_schedule(world, schedule)
    x = ensure_varying(x, axes[0])
    out = _quantized_ring_allreduce_sum(
        x.reshape(-1).astype(jnp.float32), axes[0], interpret, codec,
        sched)
    raw, encoded = qz.ring_bytes(x.size, world, codec, sched)
    qz.note_device_bytes(raw, encoded)
    if op == ReduceOp.AVERAGE:
        out = out / world
    return out.reshape(x.shape)


def _resolve_explicit_codec(codec: Optional[str]) -> str:
    """Codec for a direct quantized_* call: the configured device codec,
    falling back to int8 when the config says none (calling a quantized
    collective explicitly asks for quantization)."""
    if codec is None:
        codec = _device_codec_defaults()[0]
    if not _codec_enabled(codec):
        codec = "int8"
    return codec


def quantized_allgather(x, axis_name: AxisName,
                        min_bytes: Optional[int] = None,
                        codec: Optional[str] = None,
                        interpret: Optional[bool] = None):
    """Allgather with block-scaled encoding: each rank quantizes its shard
    ONCE, the encoded (codes, scales) payload rides ``lax.all_gather``,
    and every rank — including the owner — dequantizes all world shards
    from the same bytes, so the result is bit-identical across ranks.
    Ineligible inputs demote to :func:`allgather` bit-identically."""
    from . import quantize as qz

    if min_bytes is None:
        min_bytes = _device_codec_defaults()[1]
    axes = _axes_tuple(axis_name)
    world = 1
    for a in axes:
        world *= axis_size(a)
    if (len(axes) != 1 or getattr(x, "ndim", 0) < 1
            or not quantized_collective_eligible(x, world, min_bytes)):
        return allgather(x, axis_name)
    codec = _resolve_explicit_codec(codec)
    ax = axes[0]
    x = ensure_varying(x, ax)
    flat = x.reshape(-1)
    length = flat.shape[0]
    payload = qz.quantize(flat, codec, interpret)
    gathered = jax.tree_util.tree_map(
        lambda l: lax.all_gather(l, ax, axis=0), payload)
    shards = []
    for r in range(world):
        pr = jax.tree_util.tree_map(lambda l, _r=r: l[_r], gathered)
        shards.append(qz.dequantize(pr[0], pr[1], length, codec,
                                    interpret))
    out = jnp.stack(shards)                       # [world, length]
    qz.note_device_bytes((world - 1) * length * 4,
                         (world - 1) * qz.encoded_nbytes(length, codec))
    return out.reshape((world * x.shape[0],) + x.shape[1:])


def quantized_broadcast(x, root_rank: int, axis_name: AxisName,
                        min_bytes: Optional[int] = None,
                        codec: Optional[str] = None,
                        interpret: Optional[bool] = None):
    """Broadcast of the root's block-scaled encoding: the root quantizes,
    a masked psum moves the encoded payload (only the root contributes,
    so the summed codes/scales ARE the root's bytes — no overflow), and
    every rank — the root included — dequantizes the same encoding.  The
    result is bit-identical across ranks and within one quantization step
    (<= scale/2 per element) of the root's value, EQuARX's broadcast
    semantics.  Ineligible inputs demote to :func:`broadcast`
    bit-identically."""
    from . import quantize as qz

    if min_bytes is None:
        min_bytes = _device_codec_defaults()[1]
    axes = _axes_tuple(axis_name)
    world = 1
    for a in axes:
        world *= axis_size(a)
    if (len(axes) != 1
            or not quantized_collective_eligible(x, world, min_bytes)):
        return broadcast(x, root_rank, axis_name)
    codec = _resolve_explicit_codec(codec)
    ax = axes[0]
    x = ensure_varying(x, ax)
    flat = x.reshape(-1)
    length = flat.shape[0]
    idx = lax.axis_index(ax)
    payload = qz.quantize(flat, codec, interpret)
    payload = jax.tree_util.tree_map(
        lambda l: lax.psum(
            jnp.where(idx == root_rank, l, jnp.zeros_like(l)), ax),
        payload)
    out = qz.dequantize(payload[0], payload[1], length, codec, interpret)
    qz.note_device_bytes(length * 4, qz.encoded_nbytes(length, codec))
    return out.reshape(x.shape)


def quantized_alltoall(x, axis_name: AxisName,
                       min_bytes: Optional[int] = None,
                       codec: Optional[str] = None,
                       interpret: Optional[bool] = None):
    """Alltoall with block-scaled encoding — the MoE dispatch/combine
    path.  Each rank quantizes its world destination chunks separately
    (so every chunk decodes from its own scales), the stacked encodings
    ride ``lax.all_to_all``, and each received chunk is dequantized on
    arrival: exactly one quantization step end to end.  Ineligible inputs
    (wrong dtype, too small, or dim 0 not divisible by the axis size)
    demote to :func:`alltoall` bit-identically."""
    from . import quantize as qz

    if min_bytes is None:
        min_bytes = _device_codec_defaults()[1]
    axes = _axes_tuple(axis_name)
    world = 1
    for a in axes:
        world *= axis_size(a)
    if (len(axes) != 1 or getattr(x, "ndim", 0) < 1
            or not quantized_collective_eligible(x, world, min_bytes,
                                                 divisor=world)):
        return alltoall(x, axis_name)
    codec = _resolve_explicit_codec(codec)
    ax = axes[0]
    x = ensure_varying(x, ax)
    rows = x.reshape(world, -1)                   # destination chunks
    c = rows.shape[1]
    payloads = [qz.quantize(rows[r], codec, interpret)
                for r in range(world)]
    stacked = jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls), *payloads)
    swapped = jax.tree_util.tree_map(
        lambda l: lax.all_to_all(l, ax, split_axis=0, concat_axis=0,
                                 tiled=True),
        stacked)
    parts = []
    for r in range(world):
        pr = jax.tree_util.tree_map(lambda l, _r=r: l[_r], swapped)
        parts.append(qz.dequantize(pr[0], pr[1], c, codec, interpret))
    out = jnp.stack(parts).reshape(-1)[:x.size]
    qz.note_device_bytes((world - 1) * c * 4,
                         (world - 1) * qz.encoded_nbytes(c, codec))
    return out.reshape(x.shape)


def quantized_reducescatter(x, axis_name: AxisName,
                            op: ReduceOp = ReduceOp.SUM,
                            min_bytes: Optional[int] = None,
                            codec: Optional[str] = None,
                            interpret: Optional[bool] = None):
    """Reduce-scatter through the block-scaled ring: the reduce-scatter
    half of the quantized allreduce (world-1 hops, fp32 accumulation
    between hops), offset so rank r ends owning its own leading-dim
    chunk.  Sum and Average only; ineligible inputs demote to
    :func:`reducescatter` bit-identically."""
    from . import quantize as qz

    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"quantized_reducescatter supports Sum and Average, got {op}")
    if min_bytes is None:
        min_bytes = _device_codec_defaults()[1]
    axes = _axes_tuple(axis_name)
    world = 1
    for a in axes:
        world *= axis_size(a)
    if (len(axes) != 1 or getattr(x, "ndim", 0) < 1
            or not quantized_collective_eligible(x, world, min_bytes,
                                                 divisor=world)):
        return reducescatter(x, axis_name, op=op)
    codec = _resolve_explicit_codec(codec)
    ax = axes[0]
    x = ensure_varying(x, ax)
    rows = x.reshape(world, -1).astype(jnp.float32)
    c = rows.shape[1]
    me = lax.axis_index(ax)
    perm = [(i, (i + 1) % world) for i in range(world)]
    # off=-1: rank r starts the partial for row (r-1) % world, so after
    # world-1 hops the fully-summed row r lands on rank r — its own
    # scatter chunk.
    acc = _ring_reduce_scatter(rows, ax, world, me, -1, +1, perm, codec,
                               interpret)
    qz.note_device_bytes((world - 1) * c * 4,
                         (world - 1) * qz.encoded_nbytes(c, codec))
    if op == ReduceOp.AVERAGE:
        acc = acc / world
    return acc.reshape((x.shape[0] // world,) + x.shape[1:])
