"""In-jit (traced) collective implementations: the XLA/ICI data plane.

This is the TPU-native replacement for the reference's NCCL ops layer
(horovod/common/ops/nccl_operations.cc — NCCLAllreduce/NCCLAllgather/
NCCLBroadcast/NCCLAlltoall; SURVEY.md §2.2): where NCCL launches ring
kernels on a CUDA stream, here each collective is a ``jax.lax`` primitive
over a named mesh axis that XLA lowers onto ICI — fusion, overlap, and
scheduling come from the compiler rather than hand-managed streams.

These functions are called by ``horovod_tpu.mpi_ops`` when the input is a
JAX tracer (i.e. inside ``jit``/``shard_map``), and may also be used
directly in SPMD training code.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..wire import ReduceOp

AxisName = Union[str, Sequence[str]]


def axis_size(axis_name: AxisName) -> int:
    return lax.axis_size(axis_name)


def _axes_tuple(axis_name: AxisName):
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def ensure_varying(x, axis_name: AxisName):
    """Cast ``x`` to 'varying' over every requested axis (shard_map vma).

    Classic collective semantics treat the input as this shard's value;
    psum of a replicated value multiplies by the axis size, pmean is the
    identity.  JAX's vma typing instead *rejects* collectives over axes the
    value is invariant on — this cast restores the classic behavior at the
    public API boundary.  (Gradient reduction wants different semantics for
    invariant leaves — see optimizer._tree_allreduce.)
    """
    try:
        vma = jax.typeof(x).vma
    except Exception:
        return x
    missing = tuple(a for a in _axes_tuple(axis_name) if a not in vma)
    return lax.pcast(x, missing, to="varying") if missing else x


def allreduce(x, axis_name: AxisName, op: ReduceOp = ReduceOp.AVERAGE,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    x = ensure_varying(x, axis_name)
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    if op == ReduceOp.AVERAGE:
        out = lax.pmean(x, axis_name)
    elif op == ReduceOp.SUM:
        out = lax.psum(x, axis_name)
    elif op == ReduceOp.MIN:
        out = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        out = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        out = jnp.prod(lax.all_gather(x, axis_name, axis=0), axis=0)
    elif op == ReduceOp.ADASUM:
        out = adasum(x, axis_name)
    else:
        raise ValueError(f"unsupported reduce op {op}")
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return out


def allgather(x, axis_name: AxisName):
    """Concatenate along dim 0 across the axis (Horovod allgather semantics)."""
    return lax.all_gather(ensure_varying(x, axis_name), axis_name, axis=0,
                          tiled=True)


def broadcast(x, root_rank: int, axis_name: AxisName):
    """Every member receives root's value.

    Implemented as a masked psum — one collective, no gather of the full
    axis — which XLA lowers to an ICI broadcast-like pattern.
    """
    x = ensure_varying(x, axis_name)
    idx = lax.axis_index(axis_name)
    # where() (not multiply-by-mask) so NaN/Inf in non-root shards are
    # discarded rather than propagated through the sum.
    contribution = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return lax.psum(contribution, axis_name)


def alltoall(x, axis_name: AxisName):
    """Equal-splits alltoall: first dim is split across the axis and the
    received chunks are concatenated along dim 0 (lax.all_to_all)."""
    return lax.all_to_all(ensure_varying(x, axis_name), axis_name,
                          split_axis=0, concat_axis=0, tiled=True)


def reducescatter(x, axis_name: AxisName, op: ReduceOp = ReduceOp.SUM,
                  prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("in-jit reducescatter supports Sum and Average")
    x = ensure_varying(x, axis_name)
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    out = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    if op == ReduceOp.AVERAGE:
        out = out / lax.axis_size(axis_name)
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return out


def adasum(x, axis_name: AxisName):
    """Adasum scale-invariant reduction over a mesh axis.

    TPU-native version of the reference's recursive vector-halving/distance-
    doubling Adasum (horovod/common/ops/adasum/adasum.h; SURVEY.md §2.2):
    log2(n) rounds of pairwise combination, each round exchanging partners
    via ``ppermute`` over ICI.  For a pair (a, b):

        adasum(a, b) = (1 - a.b / (2|a|^2)) a + (1 - a.b / (2|b|^2)) b

    Requires the axis size to be a power of two (as the reference does for
    its pure Adasum path).
    """
    n = lax.axis_size(axis_name)
    if n & (n - 1) != 0:
        raise ValueError(f"Adasum requires a power-of-two axis size, got {n}")
    rounds = n.bit_length() - 1
    idx = lax.axis_index(axis_name)
    out = x
    for k in range(rounds):
        stride = 1 << k
        partner = idx ^ stride
        perm = [(i, i ^ stride) for i in range(n)]
        other = lax.ppermute(out, axis_name, perm)
        a, b = out, other
        dot = jnp.vdot(a, b).astype(jnp.float32)
        na = jnp.vdot(a, a).astype(jnp.float32)
        nb = jnp.vdot(b, b).astype(jnp.float32)
        eps = jnp.asarray(1e-30, jnp.float32)
        ca = (1.0 - dot / (2.0 * jnp.maximum(na, eps))).astype(x.dtype)
        cb = (1.0 - dot / (2.0 * jnp.maximum(nb, eps))).astype(x.dtype)
        combined = ca * a + cb * b
        # Both members of a pair compute the same combined vector (the
        # formula is symmetric), so no extra exchange is needed.
        out = combined
        del partner
    return out


def barrier(axis_name: AxisName):
    """A collective no-op that forces synchronisation across the axis."""
    token = jnp.zeros((), dtype=jnp.float32)
    return lax.psum(token, axis_name)
