"""In-jit (traced) collective implementations: the XLA/ICI data plane.

This is the TPU-native replacement for the reference's NCCL ops layer
(horovod/common/ops/nccl_operations.cc — NCCLAllreduce/NCCLAllgather/
NCCLBroadcast/NCCLAlltoall; SURVEY.md §2.2): where NCCL launches ring
kernels on a CUDA stream, here each collective is a ``jax.lax`` primitive
over a named mesh axis that XLA lowers onto ICI — fusion, overlap, and
scheduling come from the compiler rather than hand-managed streams.

These functions are called by ``horovod_tpu.mpi_ops`` when the input is a
JAX tracer (i.e. inside ``jit``/``shard_map``), and may also be used
directly in SPMD training code.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..wire import ReduceOp

AxisName = Union[str, Sequence[str]]


def axis_size(axis_name: AxisName) -> int:
    try:
        return lax.axis_size(axis_name)
    except AttributeError:  # jax < 0.5: psum of a literal 1 is the idiom
        return lax.psum(1, axis_name)


def _axes_tuple(axis_name: AxisName):
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def ensure_varying(x, axis_name: AxisName):
    """Cast ``x`` to 'varying' over every requested axis (shard_map vma).

    Classic collective semantics treat the input as this shard's value;
    psum of a replicated value multiplies by the axis size, pmean is the
    identity.  JAX's vma typing instead *rejects* collectives over axes the
    value is invariant on — this cast restores the classic behavior at the
    public API boundary.  (Gradient reduction wants different semantics for
    invariant leaves — see optimizer._tree_allreduce.)
    """
    try:
        vma = jax.typeof(x).vma
    except Exception:
        return x
    missing = tuple(a for a in _axes_tuple(axis_name) if a not in vma)
    return lax.pcast(x, missing, to="varying") if missing else x


class _Subset:
    """Static geometry of a rank subset over ONE mesh axis — the traced
    process-set bridge (reference: process_set.cc communicator subsetting;
    SURVEY.md §2.1).

    XLA exposes no subgroup collectives through shard_map in current jax
    (``axis_index_groups`` raises NotImplementedError), so subset
    collectives lower onto FULL-axis collectives with identity-masked
    contributions — on ICI the full-axis psum is bandwidth-optimal anyway,
    and every rank of the mesh executes the same SPMD program as shard_map
    requires.  Semantics: member ranks get the set's result; non-member
    ranks pass through unchanged where shapes allow (allreduce, broadcast,
    alltoall), keep their own leading s0/k chunk where the output shape
    shrinks (reducescatter), and receive the set's result where it must be
    uniform (allgather).
    """

    def __init__(self, axis_name: AxisName, member_ranks: Sequence[int]):
        if not isinstance(axis_name, str):
            raise ValueError(
                "process_set collectives run over a single mesh axis; got "
                f"axis_name={axis_name!r}")
        self.axis = axis_name
        self.n = axis_size(axis_name)
        self.members = sorted(set(int(r) for r in member_ranks))
        if not self.members:
            raise ValueError("process set has no members")
        if self.members[0] < 0 or self.members[-1] >= self.n:
            raise ValueError(
                f"process set ranks {self.members} out of range for axis "
                f"{axis_name!r} of size {self.n} (ranks map to axis indices)")
        self.k = len(self.members)
        idx = lax.axis_index(axis_name)
        mset = set(self.members)
        self.is_member = jnp.asarray(
            [i in mset for i in range(self.n)])[idx]
        # Position of this rank within the set (0 for non-members — only
        # ever used behind an is_member select).
        self.pos = jnp.asarray(
            [self.members.index(i) if i in mset else 0
             for i in range(self.n)])[idx]

    def masked(self, x, identity):
        """This rank's contribution: x for members, the op identity else."""
        return jnp.where(self.is_member, x, identity)

    def passthrough(self, result, x):
        """Set result for members; x unchanged for non-members."""
        return jnp.where(self.is_member, result, x)


def allreduce(x, axis_name: AxisName, op: ReduceOp = ReduceOp.AVERAGE,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              member_ranks: Optional[Sequence[int]] = None):
    x = ensure_varying(x, axis_name)
    if member_ranks is not None:
        # Scales apply to the set's result only; non-members pass through
        # UNCHANGED (the documented subset semantics).
        return _subset_allreduce(x, axis_name, op, member_ranks,
                                 prescale_factor, postscale_factor)
    if (op in (ReduceOp.SUM, ReduceOp.AVERAGE)
            and prescale_factor == 1.0 and postscale_factor == 1.0):
        # Device-plane codec auto-dispatch (HOROVOD_WIRE_COMPRESSION
        # device=int8): eligible fp32 payloads ride the int8 block-scaled
        # ring; everything else falls through bit-identically.  No
        # recursion: quantized_allreduce only calls back here when the
        # same eligibility test fails.
        codec, min_bytes = _device_codec_defaults()
        if codec == "int8":
            axes = ((axis_name,) if isinstance(axis_name, str)
                    else tuple(axis_name))
            if len(axes) == 1 and quantized_allreduce_eligible(
                    x, axis_size(axes[0]), min_bytes):
                return quantized_allreduce(x, axes[0], op=op,
                                           min_bytes=min_bytes)
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    if op == ReduceOp.AVERAGE:
        out = lax.pmean(x, axis_name)
    elif op == ReduceOp.SUM:
        out = lax.psum(x, axis_name)
    elif op == ReduceOp.MIN:
        out = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        out = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        out = jnp.prod(lax.all_gather(x, axis_name, axis=0), axis=0)
    elif op == ReduceOp.ADASUM:
        out = adasum(x, axis_name)
    else:
        raise ValueError(f"unsupported reduce op {op}")
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return out


def _reduce_identity(x, op: ReduceOp):
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
        return jnp.zeros_like(x)
    if op == ReduceOp.PRODUCT:
        return jnp.ones_like(x)
    if x.dtype == jnp.bool_:
        # bool Min == AND (identity True), bool Max == OR (identity False)
        if op == ReduceOp.MIN:
            return jnp.ones_like(x)
        if op == ReduceOp.MAX:
            return jnp.zeros_like(x)
        raise ValueError(f"unsupported reduce op {op}")
    info = (jnp.finfo if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo)(x.dtype)
    if op == ReduceOp.MIN:
        return jnp.full_like(x, info.max)
    if op == ReduceOp.MAX:
        return jnp.full_like(x, info.min)
    raise ValueError(f"unsupported reduce op {op}")


def _subset_allreduce(x, axis_name: str, op: ReduceOp,
                      member_ranks: Sequence[int],
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0):
    sub = _Subset(axis_name, member_ranks)
    xs = x
    if prescale_factor != 1.0:
        xs = xs * jnp.asarray(prescale_factor, dtype=x.dtype)
    if op == ReduceOp.ADASUM:
        out = adasum(xs, axis_name, member_ranks=sub.members)
    else:
        contrib = sub.masked(xs, _reduce_identity(xs, op))
        if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
            out = lax.psum(contrib, axis_name)
            if op == ReduceOp.AVERAGE:
                out = out / sub.k
        elif op == ReduceOp.MIN:
            out = lax.pmin(contrib, axis_name)
        elif op == ReduceOp.MAX:
            out = lax.pmax(contrib, axis_name)
        elif op == ReduceOp.PRODUCT:
            out = jnp.prod(lax.all_gather(contrib, axis_name, axis=0),
                           axis=0)
        else:
            raise ValueError(f"unsupported reduce op {op}")
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return sub.passthrough(out, x)


def allgather(x, axis_name: AxisName,
              member_ranks: Optional[Sequence[int]] = None):
    """Concatenate along dim 0 across the axis (Horovod allgather semantics).

    With ``member_ranks``, only the members' shards are concatenated (in
    set order); every rank of the mesh receives that concatenation (the
    output shape must be uniform across the SPMD program)."""
    x = ensure_varying(x, axis_name)
    if member_ranks is None:
        return lax.all_gather(x, axis_name, axis=0, tiled=True)
    sub = _Subset(axis_name, member_ranks)
    # One full-axis psum of a [k, s0, ...] buffer in which each member
    # deposits its own shard at its set position (non-members contribute
    # zeros): the rows are disjoint, so the sum IS the member concatenation.
    # Memory and wire bytes are O(k*s0) — not the O(n*s0) of the previous
    # full-axis all_gather + row select, an n/k blowup exactly when the set
    # is small relative to the mesh — and psum's vma semantics make the
    # result axis-invariant (replicated), so out_specs expecting
    # replication keep working.
    row = sub.masked(x, jnp.zeros_like(x))
    # psum converts bool inputs to integers; round-trip through int32 so
    # the output dtype matches the input (as the reference's allgather does).
    calc_dtype = jnp.int32 if x.dtype == jnp.bool_ else x.dtype
    contrib = jnp.zeros((sub.k,) + x.shape, calc_dtype)
    contrib = lax.dynamic_update_slice(
        contrib, row[None].astype(calc_dtype), (sub.pos,) + (0,) * x.ndim)
    full = lax.psum(contrib, axis_name)                # [k, s0, ...]
    return full.reshape(
        (sub.k * x.shape[0],) + x.shape[1:]).astype(x.dtype)


def broadcast(x, root_rank: int, axis_name: AxisName,
              member_ranks: Optional[Sequence[int]] = None):
    """Every member receives root's value (``root_rank`` is the GLOBAL
    rank / axis index, as in the reference's process-set broadcast —
    socket_controller.cc resolves it within the member list).

    Implemented as a masked psum — one collective, no gather of the full
    axis — which XLA lowers to an ICI broadcast-like pattern.
    """
    x = ensure_varying(x, axis_name)
    idx = lax.axis_index(axis_name)
    sub = None
    if member_ranks is not None:
        sub = _Subset(axis_name, member_ranks)
        if int(root_rank) not in sub.members:
            raise ValueError(
                f"broadcast root {root_rank} is not in the process set "
                f"{sub.members}")
    # where() (not multiply-by-mask) so NaN/Inf in non-root shards are
    # discarded rather than propagated through the sum.
    contribution = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    out = lax.psum(contribution, axis_name)
    return out if sub is None else sub.passthrough(out, x)


def alltoall(x, axis_name: AxisName,
             member_ranks: Optional[Sequence[int]] = None):
    """Equal-splits alltoall: first dim is split across the axis and the
    received chunks are concatenated along dim 0 (lax.all_to_all).

    With ``member_ranks``, dim 0 is split |set| ways and exchanged among
    the members only; non-members pass through unchanged."""
    x = ensure_varying(x, axis_name)
    if member_ranks is None:
        return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
    sub = _Subset(axis_name, member_ranks)
    s0 = x.shape[0]
    if s0 % sub.k:
        raise ValueError(
            f"alltoall dim 0 ({s0}) must divide by the process-set size "
            f"({sub.k})")
    c = s0 // sub.k
    n = sub.n
    # k ppermute rounds of one [c, ...] chunk each — total bytes moved
    # equal the baseline alltoall (a full-axis all_gather here would be an
    # n-times memory blowup).  In round t, the member at set position p
    # sends its chunk (p+t)%k to the member at position (p+t)%k, who
    # stores it at slot p = (recv_pos - t) % k.  Non-members self-send
    # and are patched through at the end.
    out = jnp.zeros_like(x)
    for t in range(sub.k):
        send_start = ((sub.pos + t) % sub.k) * c
        chunk = lax.dynamic_slice_in_dim(x, send_start, c, axis=0)
        if t == 0:
            moved = chunk
        else:
            pair = {sub.members[p]: sub.members[(p + t) % sub.k]
                    for p in range(sub.k)}
            perm = [(i, pair.get(i, i)) for i in range(n)]
            moved = lax.ppermute(chunk, axis_name, perm)
        recv_start = ((sub.pos - t) % sub.k) * c
        out = lax.dynamic_update_slice_in_dim(out, moved, recv_start,
                                              axis=0)
    return sub.passthrough(out, x)


def reducescatter(x, axis_name: AxisName, op: ReduceOp = ReduceOp.SUM,
                  prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                  member_ranks: Optional[Sequence[int]] = None):
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("in-jit reducescatter supports Sum and Average")
    x = ensure_varying(x, axis_name)
    if member_ranks is not None:
        sub = _Subset(axis_name, member_ranks)
        s0 = x.shape[0]
        if s0 % sub.k:
            raise ValueError(
                f"reducescatter dim 0 ({s0}) must divide by the "
                f"process-set size ({sub.k})")
        c = s0 // sub.k
        xs = x
        if prescale_factor != 1.0:
            xs = xs * jnp.asarray(prescale_factor, dtype=x.dtype)
        summed = lax.psum(sub.masked(xs, jnp.zeros_like(xs)), axis_name)
        out = lax.dynamic_slice_in_dim(summed, sub.pos * c, c, axis=0)
        if op == ReduceOp.AVERAGE:
            out = out / sub.k
        if postscale_factor != 1.0:
            out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
        # Non-members keep their own leading chunk UNSCALED (shape-uniform
        # pass-through analog).
        return jnp.where(sub.is_member, out,
                         lax.slice_in_dim(x, 0, c, axis=0))
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    out = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    if op == ReduceOp.AVERAGE:
        out = out / axis_size(axis_name)
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return out


def adasum(x, axis_name: AxisName,
           member_ranks: Optional[Sequence[int]] = None):
    """Adasum scale-invariant reduction over a mesh axis.

    TPU-native version of the reference's recursive vector-halving/distance-
    doubling Adasum (horovod/common/ops/adasum/adasum.h; SURVEY.md §2.2):
    log2(n) rounds of pairwise combination, each round exchanging partners
    via ``ppermute`` over ICI.  For a pair (a, b):

        adasum(a, b) = (1 - a.b / (2|a|^2)) a + (1 - a.b / (2|b|^2)) b

    Requires the axis size to be a power of two (as the reference does for
    its pure Adasum path).  With ``member_ranks`` the pairwise rounds run
    among the members only (|set| must be a power of two); non-members
    ppermute to themselves, and adasum(a, a) = a leaves them unchanged.
    """
    n = axis_size(axis_name)
    if member_ranks is not None:
        members = sorted(set(int(r) for r in member_ranks))
    else:
        members = list(range(n))
    m = len(members)
    if m & (m - 1) != 0:
        raise ValueError(f"Adasum requires a power-of-two size, got {m}")
    rounds = m.bit_length() - 1
    out = x
    for k in range(rounds):
        stride = 1 << k
        # Pair set-positions p <-> p^stride, mapped back to global axis
        # indices; everyone else exchanges with itself.
        pair = {members[p]: members[p ^ stride] for p in range(m)}
        perm = [(i, pair.get(i, i)) for i in range(n)]
        other = lax.ppermute(out, axis_name, perm)
        a, b = out, other
        dot = jnp.vdot(a, b).astype(jnp.float32)
        na = jnp.vdot(a, a).astype(jnp.float32)
        nb = jnp.vdot(b, b).astype(jnp.float32)
        eps = jnp.asarray(1e-30, jnp.float32)
        ca = (1.0 - dot / (2.0 * jnp.maximum(na, eps))).astype(x.dtype)
        cb = (1.0 - dot / (2.0 * jnp.maximum(nb, eps))).astype(x.dtype)
        combined = ca * a + cb * b
        # Both members of a pair compute the same combined vector (the
        # formula is symmetric), so no extra exchange is needed.
        out = combined
    return out


def barrier(axis_name: AxisName):
    """A collective no-op that forces synchronisation across the axis."""
    token = jnp.zeros((), dtype=jnp.float32)
    return lax.psum(token, axis_name)


# --- Quantized (int8 block-scaled) ring allreduce -------------------------

def _device_codec_defaults():
    """(codec, min_bytes) from the live context when initialized, else from
    the environment — trace-time only, never inside the compiled program."""
    try:
        from ..context import HorovodContext
        if HorovodContext.initialized():
            cfg = HorovodContext.instance().cfg
            return (getattr(cfg, "wire_compression_device", "none"),
                    getattr(cfg, "wire_compression_min_bytes", 1 << 16))
    except Exception:
        pass
    from ..utils.env import get_int, get_wire_compression_planes
    return (get_wire_compression_planes()[1],
            get_int("HOROVOD_WIRE_COMPRESSION_MIN_BYTES", 1 << 16))


def quantized_allreduce_eligible(x, world: int, min_bytes: int) -> bool:
    """Demotion rule for the device-plane int8 codec, shared by the traced
    path, the optimizer's error-feedback gate, and the eager device plane
    so every layer falls the same way: fp32 only (quantizing low-precision
    or integer payloads either loses exactness or gains nothing), at least
    ``min_bytes`` of payload (small tensors are latency-bound and the
    per-block scale overhead erodes the ratio), and a real ring to run on.
    """
    dtype = getattr(x, "dtype", None)
    size = 1
    for d in getattr(x, "shape", ()):  # static under jit
        size *= int(d)
    return (world > 1 and dtype == jnp.float32
            and size * 4 >= int(min_bytes))


def _quantized_ring_allreduce_sum(flat, axis_name: str,
                                  interpret: Optional[bool] = None):
    """Int8 block-scaled ring reduce-scatter + all-gather over ONE mesh
    axis (the traced mirror of the host ring's int8 wire codec).

    Reduce-scatter: world-1 ``ppermute`` hops; each hop quantizes the
    running partial with ``ops.quantize`` (256-element blocks, scale =
    max|x|/127 — cpp/wire_codec.h semantics exactly), moves codes + scales
    to the next rank, and accumulates in fp32 against the receiver's own
    contribution (the ring never adds quantized values together).

    All-gather: the owner quantizes its fully-reduced chunk ONCE and the
    encoded representation is forwarded verbatim around the ring — every
    rank dequantizes the same codes and scales, so the result is
    bit-identical across ranks (the same verbatim-forwarding rule the host
    codec uses for its allgather phase).
    """
    from . import quantize as qz

    n = axis_size(axis_name)
    length = flat.shape[0]
    chunk = -(-length // n)
    x = jnp.pad(flat, (0, n * chunk - length)) if n * chunk != length else flat
    chunks = x.reshape(n, chunk)
    me = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Reduce-scatter: rank r starts the partial for chunk r; after world-1
    # hops the fully-summed chunk (r+1) % n lands on rank r.
    acc = lax.dynamic_index_in_dim(chunks, me, 0, keepdims=False)
    for t in range(n - 1):
        qb, scales = qz.quantize(acc, interpret)
        qb = lax.ppermute(qb, axis_name, perm)
        scales = lax.ppermute(scales, axis_name, perm)
        own = lax.dynamic_index_in_dim(
            chunks, jnp.mod(me - t - 1, n), 0, keepdims=False)
        acc = qz.dequantize(qb, scales, chunk, interpret) + own

    # All-gather: encode once, forward the encoding verbatim.
    qb, scales = qz.quantize(acc, interpret)
    out = jnp.zeros((n, chunk), jnp.float32)
    out = ensure_varying(out, axis_name)
    for t in range(n):
        piece = qz.dequantize(qb, scales, chunk, interpret)
        out = lax.dynamic_update_index_in_dim(
            out, piece, jnp.mod(me - t + 1, n), 0)
        if t < n - 1:
            qb = lax.ppermute(qb, axis_name, perm)
            scales = lax.ppermute(scales, axis_name, perm)
    return out.reshape(-1)[:length]


def quantized_allreduce(x, axis_name: AxisName,
                        op: ReduceOp = ReduceOp.SUM,
                        min_bytes: Optional[int] = None,
                        interpret: Optional[bool] = None):
    """Allreduce through the int8 block-scaled ring when ``x`` is eligible;
    otherwise demotes to the plain (uncompressed) collective, bit-identical
    to :func:`allreduce`.

    ``min_bytes=None`` reads HOROVOD_WIRE_COMPRESSION_MIN_BYTES (context
    config when initialized).  Byte accounting
    (``data_plane_stats()['device_raw'/'device_encoded']``) is recorded per
    trace — under ``jax.jit`` cache reuse the program moves the same bytes
    every call, so the per-trace note is the per-call wire cost.
    """
    from . import quantize as qz

    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"quantized_allreduce supports Sum and Average, got {op}")
    if min_bytes is None:
        min_bytes = _device_codec_defaults()[1]
    axes = _axes_tuple(axis_name)
    world = 1
    for a in axes:
        world *= axis_size(a)
    if (len(axes) != 1
            or not quantized_allreduce_eligible(x, world, min_bytes)):
        return allreduce(x, axis_name, op=op)
    x = ensure_varying(x, axes[0])
    out = _quantized_ring_allreduce_sum(
        x.reshape(-1).astype(jnp.float32), axes[0], interpret)
    raw, encoded = qz.ring_bytes(x.size, world)
    qz.note_device_bytes(raw, encoded)
    if op == ReduceOp.AVERAGE:
        out = out / world
    return out.reshape(x.shape)
