from . import collectives  # noqa: F401
