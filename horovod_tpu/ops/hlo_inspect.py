"""Compiled-collective introspection for the gspmd data plane.

The gspmd plane (ops/gspmd_plane.py) never builds a collective: it
annotates shardings and lets ``jax.jit``'s SPMD partitioner insert and
schedule the collectives itself.  That makes it the one data plane the
observability pillars cannot see — no enqueue, no ring hop, no byte
counter ever fires.  This module closes the gap at the only place the
plane is visible: the *compiled* HLO module.

At trace time (once per abstract-argument signature, never per step) an
instrumented train step is lowered and compiled, the optimized module
text is walked, and every compiler-inserted collective is inventoried:
op kind (all-reduce / all-gather / reduce-scatter / collective-permute /
all-to-all, async ``-start`` forms counted once), element type, shape,
replica-group size, and analytic wire bytes under the ring model the
host and device planes already use:

- all-reduce:          ``2 * payload * (g - 1) / g``  (reduce-scatter +
  all-gather halves of the ring algorithm);
- all-gather / reduce-scatter / all-to-all: ``payload * (g - 1) / g``
  (each rank ships every shard but its own);
- collective-permute:  ``payload`` (one full hop).

``payload`` is the logical full-tensor byte count and ``g`` the
replica-group size.  The inventory then feeds every pillar: the native
gspmd byte counters (``hvd.metrics()`` / ``data_plane_stats()`` /
``hvd_gspmd_*`` Prometheus series) via :func:`set_native_sink`, a
type-16 ``hloinspect`` flight-recorder event (a = op count, b = wire
bytes), and the step-trace plane tag so ``tools/critical_path.py`` and
the cockpit attribute steps to the plane.  ``tools/hlo_report.py``
renders the same inventory offline.

Cost discipline: ``HOROVOD_HLO_INSPECT=0`` makes :func:`instrument`
return its argument unchanged — zero per-step work.  Enabled, the only
per-step cost is an abstract-signature cache lookup; the lower + compile
+ parse happens once per new signature.  Inspection is gated on the
resolved plane (the optimizer marks traces via :func:`mark_plane`), so
eager shard_map/psum traces — whose HLO also contains all-reduce ops the
explicit pillars already count — report an empty inventory rather than
double-counted bytes.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.env import get_bool

# Collective op kinds inventoried (HLO opcode names, sync form).
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

# HLO element-type bit widths (shape tokens like ``f32[64,8]``).
_DTYPE_BITS = {
    "pred": 8, "s4": 4, "u4": 4, "s8": 8, "u8": 8,
    "f8e4m3fn": 8, "f8e5m2": 8, "s16": 16, "u16": 16, "f16": 16,
    "bf16": 16, "s32": 32, "u32": 32, "f32": 32,
    "s64": 64, "u64": 64, "f64": 64, "c64": 64, "c128": 128,
}

# ``%name = <shape> all-reduce(...)`` — the shape part is captured lazily
# up to the opcode so tuple shapes (variadic / async forms) survive.
# ``-done`` halves of async pairs are skipped (the ``-start`` carries the
# shape and the replica groups; counting both would double every op).
_COLLECTIVE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<shape>.*?)\s*"
    r"(?P<kind>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?P<variant>-start|-done)?\(")
_SHAPE_TOKEN_RE = re.compile(
    r"(pred|bf16|f8e4m3fn|f8e5m2|[fsuc]\d+)\[([0-9,]*)\]")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([0-9, ]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")


def enabled() -> bool:
    """HOROVOD_HLO_INSPECT gate (default on).  Reads the live context's
    config when initialized, the environment otherwise — same fallback
    shape as the plane default (ops/gspmd_plane.py)."""
    try:
        from ..context import HorovodContext
        if HorovodContext.initialized():
            return bool(getattr(HorovodContext.instance().cfg,
                                "hlo_inspect_enabled", True))
    except Exception:
        pass
    return get_bool("HOROVOD_HLO_INSPECT", True)


# ---------------------------------------------------------------------------
# Inventory model
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CollectiveOp:
    """One compiler-inserted collective from an optimized HLO module."""

    kind: str            # sync opcode name ("all-reduce", ...)
    name: str            # HLO instruction name
    dtype: str           # element type of the first payload operand
    shape: str           # result shape as printed in the module
    elements: int        # payload element count (summed over tuple parts)
    raw_bytes: int       # logical full-tensor bytes exchanged
    group_size: int      # replica-group size g (world when ungrouped)
    wire_bytes: int      # analytic ring-model wire bytes
    asynchronous: bool   # came from an async -start form

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class TraceInventory:
    """Every collective of one compiled gspmd-plane trace."""

    label: str
    world: int                       # module partition count
    ops: List[CollectiveOp]
    raw_bytes: int
    wire_bytes: int
    cost: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def collectives(self) -> int:
        return len(self.ops)

    def kind_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.ops:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {"label": self.label, "world": self.world,
                "collectives": self.collectives,
                "kinds": self.kind_counts(),
                "raw_bytes": self.raw_bytes,
                "wire_bytes": self.wire_bytes,
                "cost": dict(self.cost),
                "ops": [op.to_dict() for op in self.ops]}


def ring_wire_bytes(kind: str, raw_bytes: int, group_size: int) -> int:
    """Analytic per-device wire bytes for one collective of ``raw_bytes``
    logical payload over a replica group of ``group_size`` (module
    docstring).  Exact integer arithmetic so every consumer — the live
    counters, the tests, tools/hlo_report.py — reproduces the same
    totals bit-for-bit."""
    g = max(1, int(group_size))
    raw = int(raw_bytes)
    if g <= 1:
        return 0
    if kind == "all-reduce":
        return (2 * raw * (g - 1)) // g
    if kind in ("all-gather", "reduce-scatter", "all-to-all"):
        return (raw * (g - 1)) // g
    return raw  # collective-permute: one full hop


def _shape_tokens(shape: str) -> List[Tuple[str, int, int]]:
    """[(dtype, elements, bytes)] per payload token of a printed shape.
    Sub-byte and non-8-multiple widths round up per token."""
    toks: List[Tuple[str, int, int]] = []
    for dt, dims in _SHAPE_TOKEN_RE.findall(shape):
        bits = _DTYPE_BITS.get(dt)
        if bits is None:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        toks.append((dt, n, (n * bits + 7) // 8))
    return toks


def _shape_payload(shape: str) -> Tuple[str, int, int]:
    """(dtype, elements, bytes) summed over a shape's payload tokens."""
    toks = _shape_tokens(shape)
    if not toks:
        return "", 0, 0
    return (toks[0][0], sum(t[1] for t in toks), sum(t[2] for t in toks))


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len([t for t in m.group(1).split(",") if t.strip()])
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(2))
    return max(1, int(default))


def module_partitions(text: str) -> int:
    """Partition count from the module header (0 when unstated)."""
    m = _PARTITIONS_RE.search(text)
    return int(m.group(1)) if m else 0


def inventory_from_text(text: str, world: int = 0,
                        label: str = "") -> TraceInventory:
    """Walk optimized HLO module text and inventory every collective.

    ``world`` defaults to the module's own ``num_partitions`` header (1
    when absent).  Pure text analysis — usable offline on dumped modules
    (tools/hlo_report.py) as well as on live Compiled objects.
    """
    if world <= 0:
        world = module_partitions(text) or 1
    ops: List[CollectiveOp] = []
    for line in text.splitlines():
        m = _COLLECTIVE_RE.match(line)
        if m is None:
            continue
        if m.group("variant") == "-done":
            continue  # async pair: the -start already carried the op
        kind = m.group("kind")
        dtype, elements, nbytes = _shape_payload(m.group("shape"))
        if elements == 0 and nbytes == 0:
            continue
        g = _group_size(line, world)
        asynchronous = m.group("variant") == "-start"
        if asynchronous:
            # A -start's tuple shape carries (operand, result, ...); the
            # logical payload is the result alone, so summing the tuple
            # would double-count.
            if kind == "all-gather":
                # The gathered result is the largest tuple part.
                toks = _shape_tokens(m.group("shape"))
                if toks:
                    dtype, elements, nbytes = max(toks, key=lambda t: t[2])
            else:
                # all-reduce / collective-permute: operand and result
                # shapes alias — halve the summed pair.
                nbytes //= 2
                elements //= 2
        raw = nbytes * g if kind == "reduce-scatter" else nbytes
        ops.append(CollectiveOp(
            kind=kind, name=m.group("name"), dtype=dtype,
            shape=m.group("shape"), elements=elements, raw_bytes=raw,
            group_size=g, wire_bytes=ring_wire_bytes(kind, raw, g),
            asynchronous=asynchronous))
    return TraceInventory(
        label=label, world=world, ops=ops,
        raw_bytes=sum(op.raw_bytes for op in ops),
        wire_bytes=sum(op.wire_bytes for op in ops))


# ---------------------------------------------------------------------------
# Counters and the native sink (mirror of ops/quantize.py's byte pair)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_RAW = 0
_WIRE = 0
_OPS = 0
_TRACES = 0
_INVENTORIES: List[TraceInventory] = []
_MAX_INVENTORIES = 32
_NATIVE_SINK: Optional[Callable[[int, int, int], None]] = None


def set_native_sink(fn: Optional[Callable[[int, int, int], None]]) -> None:
    """Register a callable forwarding (ops, raw, wire) per inspected
    trace to the native metrics registry (NativeCore wires
    hvd_gspmd_plane_note here) so the inventory shows up in
    hvd.metrics() / Prometheus and as a type-16 flight event."""
    global _NATIVE_SINK
    _NATIVE_SINK = fn


def note_inventory(inv: TraceInventory) -> None:
    """Record one inspected trace: Python-side counters (the stale-.so
    fallback data_plane_stats() reads), the bounded inventory ring, and
    the native sink."""
    global _RAW, _WIRE, _OPS, _TRACES
    with _LOCK:
        _RAW += inv.raw_bytes
        _WIRE += inv.wire_bytes
        _OPS += inv.collectives
        _TRACES += 1
        _INVENTORIES.append(inv)
        del _INVENTORIES[:-_MAX_INVENTORIES]
    sink = _NATIVE_SINK
    if sink is not None:
        try:
            sink(int(inv.collectives), int(inv.raw_bytes),
                 int(inv.wire_bytes))
        except Exception:
            pass


def gspmd_byte_counters() -> Tuple[int, int]:
    """(raw, wire) analytic byte totals over every inspected trace."""
    with _LOCK:
        return (_RAW, _WIRE)


def counters() -> Dict[str, int]:
    with _LOCK:
        return {"gspmd_collectives_total": _OPS, "gspmd_raw_bytes": _RAW,
                "gspmd_wire_bytes": _WIRE, "gspmd_traces_total": _TRACES}


def inventories() -> List[TraceInventory]:
    """The most recent inspected-trace inventories, oldest first."""
    with _LOCK:
        return list(_INVENTORIES)


def reset() -> None:
    """Clear counters, inventories and the plane memo (tests)."""
    global _RAW, _WIRE, _OPS, _TRACES
    with _LOCK:
        _RAW = _WIRE = _OPS = _TRACES = 0
        _INVENTORIES.clear()
    _STEP_PLANE[0] = -2


# ---------------------------------------------------------------------------
# Plane coupling: the optimizer marks traces, instrument() gates on it
# ---------------------------------------------------------------------------

_TRACE_TLS = threading.local()
_STEP_PLANE = [-2]  # last plane noted natively; -2 = never
_PLANE_IDS = {"eager": 0, "gspmd": 1}


def _note_step_plane(plane_id: int) -> None:
    if _STEP_PLANE[0] == plane_id:
        return
    _STEP_PLANE[0] = plane_id
    try:
        from ..context import HorovodContext
        if HorovodContext.initialized():
            HorovodContext.instance().core.step_trace_note_plane(plane_id)
    except Exception:
        pass


def mark_plane(plane: str) -> None:
    """Called by DistributedOptimizer when an update resolves to a plane
    ("eager" / "gspmd"): tags the trace being formed in this thread (the
    gspmd gate for :func:`instrument`) and stamps the sticky step-trace
    plane tag natively (dedup'd, so the eager per-step path pays one list
    compare after the first note)."""
    _TRACE_TLS.plane = plane
    pid = _PLANE_IDS.get(plane, -1)
    if pid >= 0:
        _note_step_plane(pid)


def _begin_trace() -> None:
    _TRACE_TLS.plane = None


def _end_trace() -> Optional[str]:
    return getattr(_TRACE_TLS, "plane", None)


# ---------------------------------------------------------------------------
# Live inspection of jitted callables
# ---------------------------------------------------------------------------

def _compiled_text(compiled) -> str:
    try:
        mods = compiled.hlo_modules()
        if mods:
            return "\n".join(m.to_string() for m in mods)
    except Exception:
        pass
    try:
        return compiled.as_text()
    except Exception:
        return ""


def _cost_summary(compiled) -> Dict[str, float]:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    out = {}
    try:
        for key in ("flops", "bytes accessed", "optimal_seconds"):
            if key in ca:
                out[key.replace(" ", "_")] = float(ca[key])
    except Exception:
        return {}
    return out


def _abstract_signature(args, kwargs) -> tuple:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            sig.append(("py", repr(type(leaf))))
        else:
            sig.append((tuple(shape), str(dtype)))
    return (str(treedef), tuple(sig))


def inspect_lowered(lowered, label: str = "") -> Optional[TraceInventory]:
    """Compile a ``jax.jit(...).lower(...)`` result and inventory its
    compiled module.  Returns None when nothing could be compiled or the
    module text is unavailable; the inventory is NOT recorded into the
    counters — callers decide (``instrument`` records only resolved-gspmd
    traces)."""
    try:
        compiled = lowered.compile()
        text = _compiled_text(compiled)
        if not text:
            return None
        inv = inventory_from_text(text, label=label)
        inv.cost = _cost_summary(compiled)
        return inv
    except Exception:
        return None


def instrument(fn, label: Optional[str] = None):
    """Wrap a jitted train step with trace-time collective introspection.

    On the first call per abstract-argument signature the wrapper lowers
    ``fn`` (running the optimizer's trace-time plane resolution), and —
    only when the trace resolved to the gspmd plane — compiles the
    lowered module, inventories its collectives and feeds the pillars
    via :func:`note_inventory`.  Every later call with the same
    signature is a dict lookup followed by the undecorated ``fn``.

    With HOROVOD_HLO_INSPECT=0 the callable is returned unchanged: the
    instrumented and uninstrumented steps are then the same object, the
    zero-overhead bar bench_negotiation.py --hlo-inspect measures.
    """
    if not enabled():
        return fn
    import functools

    import jax

    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    name = label or getattr(fn, "__name__", "step")
    seen: Dict[tuple, bool] = {}
    lock = threading.Lock()

    def wrapper(*args, **kwargs):
        key = _abstract_signature(args, kwargs)
        with lock:
            first = key not in seen
            if first:
                seen[key] = True
        if first:
            try:
                _begin_trace()
                lowered = jfn.lower(*args, **kwargs)
                plane = _end_trace()
            except Exception:
                plane = None
            if plane == "gspmd":
                inv = inspect_lowered(lowered, label=name)
                if inv is not None:
                    note_inventory(inv)
        return jfn(*args, **kwargs)

    try:
        functools.update_wrapper(wrapper, fn)
    except Exception:
        pass
    return wrapper
