"""Int8 block-scaled quantization for the device plane (traced/XLA path).

This is the in-``jit`` mirror of the host ring's int8 wire codec
(``cpp/wire_codec.h``): the same 256-element block geometry, the same
``scale = max|x| / 127`` rule, and the same all-zero / non-finite-block
handling, so a tensor quantized on the device plane decodes to exactly the
values the host codec would have produced.  EQuARX (PAPERS.md) is the
design reference: block-scaled int8 inside the XLA program keeps the
compression on-chip — no host transfers — while fp32 accumulation between
hops preserves reduction accuracy.

Layout: a flat fp32 tensor is viewed as ``[nblocks, WIRE_BLOCK]`` (the last
block zero-padded; zeros cannot raise ``max|x|``, so a short last block
quantizes exactly as the byte-stream codec quantizes it).  Quantization
yields an int8 code array plus one fp32 scale per block — together the
traced analog of the wire stream's ``[scale][codes]`` block records, and
what actually rides ``lax.ppermute`` between devices.

The kernels are Pallas with the same dispatch rules as
``ops/flash_attention.py``: on TPU the Pallas kernel runs natively,
off-TPU the public entry points fall back to an identical-math jnp
implementation, and ``interpret=True`` forces the kernels through the
Pallas interpreter (tests).

Byte accounting: every quantized collective calls :func:`note_device_bytes`
with the raw-vs-encoded wire byte counts so the realized compression ratio
is observable (``data_plane_stats()['device_raw'/'device_encoded']``,
``hvd.metrics()``, Prometheus).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# --- Block geometry and codec ids: MUST mirror cpp/wire_codec.h ----------
# (tools/hvd_lint.py's wire-codec pass checks these against the header; a
# drift fails lint.)
WIRE_BLOCK = 256           # kWireBlock: elements per fp32 scale
WIRE_SCALE_BYTES = 4       # kWireScaleBytes: little-endian fp32 scale
WIRE_CODEC_IDS = {"none": 0, "bf16": 1, "int8": 2}   # enum class WireCodec
# Codecs the device plane can engage.  bf16 stays host-only: on-chip the
# bf16 cast is a plain convert_element_type XLA already fuses — only the
# block-scaled int8 path needs a codec implementation here.
DEVICE_WIRE_CODECS = ("none", "int8")

# Rows per Pallas grid step: 32 sublanes satisfies the int8 (32, 128) and
# fp32 (8, 128) minimum tile constraints simultaneously (WIRE_BLOCK = 256
# lanes is a multiple of 128).
_QUANT_ROWS = 32


def encoded_nbytes(count: int) -> int:
    """Wire bytes for ``count`` fp32 elements under the int8 codec — the
    same formula as WireEncodedBytes(kInt8, count)."""
    blocks = -(-int(count) // WIRE_BLOCK)
    return blocks * WIRE_SCALE_BYTES + int(count)


def ring_bytes(count: int, world: int) -> Tuple[int, int]:
    """Per-rank (raw, encoded) wire bytes for one quantized ring allreduce
    of ``count`` fp32 elements over ``world`` ranks: reduce-scatter plus
    all-gather, world-1 hops each, one chunk of ``ceil(count/world)``
    elements per hop."""
    world = max(1, int(world))
    if world == 1:
        return (0, 0)
    chunk = -(-int(count) // world)
    hops = 2 * (world - 1)
    return (hops * chunk * 4, hops * encoded_nbytes(chunk))


# --- Device-plane byte counters ------------------------------------------

_DEV_LOCK = threading.Lock()
_DEV_RAW = 0
_DEV_ENCODED = 0
_NATIVE_SINK: Optional[Callable[[int, int], None]] = None


def set_native_byte_sink(fn: Optional[Callable[[int, int], None]]) -> None:
    """Register a callable forwarding (raw, encoded) deltas to the native
    metrics registry (NativeCore wires hvd_device_plane_note here) so the
    counters show up in hvd.metrics() / Prometheus."""
    global _NATIVE_SINK
    _NATIVE_SINK = fn


def note_device_bytes(raw: int, encoded: int) -> None:
    global _DEV_RAW, _DEV_ENCODED
    with _DEV_LOCK:
        _DEV_RAW += int(raw)
        _DEV_ENCODED += int(encoded)
    sink = _NATIVE_SINK
    if sink is not None:
        try:
            sink(int(raw), int(encoded))
        except Exception:
            pass


def device_byte_counters() -> Tuple[int, int]:
    with _DEV_LOCK:
        return (_DEV_RAW, _DEV_ENCODED)


def reset_device_byte_counters() -> None:
    global _DEV_RAW, _DEV_ENCODED
    with _DEV_LOCK:
        _DEV_RAW = 0
        _DEV_ENCODED = 0


# --- Block-form reference implementation (identical math to WireEncode) --

def _block_scales(xb):
    """Per-block (scale, inv) mirroring WireEncode(kInt8) bit-for-bit:

    - max|x| scans with ``a > maxabs`` so NaN elements never win the max
      (an all-NaN block keeps scale 0 and encodes zeros);
    - a block whose max is inf gets a non-finite scale -> codes all zero
      (the stored scale stays inf, so decode flags the block as NaN rather
      than inventing values).

    ``inv`` is 0 exactly for the all-zero / non-finite blocks (a finite
    positive scale can never reciprocate to 0 in fp32), so ``inv > 0`` is
    the block-ok predicate downstream.  Computed in plain jnp — XLA's
    fp32 divide is correctly rounded, matching the C++ divides; the Pallas
    interpreter's is not, which is why the divides live outside the kernel.
    """
    absx = jnp.abs(xb)
    maxabs = jnp.max(jnp.where(jnp.isnan(absx), 0.0, absx),
                     axis=1, keepdims=True)
    scale = maxabs / 127.0
    ok = (scale > 0.0) & jnp.isfinite(scale)
    inv = jnp.where(ok, 1.0 / jnp.where(ok, scale, 1.0), 0.0)
    return scale.astype(jnp.float32), inv.astype(jnp.float32)


def _quantize_codes_ref(xb, inv):
    """Elementwise half of WireEncode(kInt8): round, clamp, block gate.

    Clamping uses std::min/std::max operand order, under which a NaN
    element inside an otherwise-finite block lands on +127 (exactly what
    the C++ loop produces)."""
    v = jnp.round(xb * inv)
    v = jnp.where(v < 127.0, v, 127.0)      # std::min(127, v): NaN -> 127
    v = jnp.where(v > -127.0, v, -127.0)    # std::max(-127, v)
    return jnp.where(inv > 0.0, v, 0.0).astype(jnp.int8)


def _quantize_blocks_ref(xb):
    """jnp mirror of WireEncode(kInt8) on [nblocks, WIRE_BLOCK] fp32."""
    scale, inv = _block_scales(xb)
    return _quantize_codes_ref(xb, inv), scale


def _dequantize_blocks_ref(qb, scales):
    """jnp mirror of WireDecodeRange(kInt8): scale * code, in fp32."""
    return scales.astype(jnp.float32) * qb.astype(jnp.float32)


# --- Pallas kernels -------------------------------------------------------

def _quant_kernel(x_ref, inv_ref, q_ref):
    # Elementwise only (mul/round/compare/select are exactly rounded on
    # every backend, so interpret mode is bit-identical to the jnp
    # fallback); the per-block scale/inv reduction rides in from jnp.
    x = x_ref[...]                                    # [ROWS, WIRE_BLOCK]
    inv = inv_ref[...]                                # [ROWS, 1]
    v = jnp.round(x * inv)
    v = jnp.where(v < 127.0, v, 127.0)
    v = jnp.where(v > -127.0, v, -127.0)
    q_ref[...] = jnp.where(inv > 0.0, v, 0.0).astype(jnp.int8)


def _dequant_kernel(q_ref, s_ref, x_ref):
    x_ref[...] = s_ref[...] * q_ref[...].astype(jnp.float32)


def _pad_rows(xb, rows: int):
    nb = xb.shape[0]
    nb_pad = -(-nb // rows) * rows
    if nb_pad != nb:
        xb = jnp.pad(xb, ((0, nb_pad - nb), (0, 0)))
    return xb, nb


def _quantize_blocks_pallas(xb, interpret: bool):
    scale, inv = _block_scales(xb)
    xb, nb = _pad_rows(xb, _QUANT_ROWS)
    inv_p, _ = _pad_rows(inv, _QUANT_ROWS)
    grid = (xb.shape[0] // _QUANT_ROWS,)
    q = pl.pallas_call(
        _quant_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((_QUANT_ROWS, WIRE_BLOCK), lambda i: (i, 0)),
                  pl.BlockSpec((_QUANT_ROWS, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_QUANT_ROWS, WIRE_BLOCK), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((xb.shape[0], WIRE_BLOCK), jnp.int8),
        interpret=interpret,
    )(xb, inv_p)
    return q[:nb], scale


def _dequantize_blocks_pallas(qb, scales, interpret: bool):
    qb, nb = _pad_rows(qb, _QUANT_ROWS)
    scales, _ = _pad_rows(scales, _QUANT_ROWS)
    grid = (qb.shape[0] // _QUANT_ROWS,)
    x = pl.pallas_call(
        _dequant_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((_QUANT_ROWS, WIRE_BLOCK), lambda i: (i, 0)),
                  pl.BlockSpec((_QUANT_ROWS, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_QUANT_ROWS, WIRE_BLOCK), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((qb.shape[0], WIRE_BLOCK),
                                       jnp.float32),
        interpret=interpret,
    )(qb, scales)
    return x[:nb]


def _dispatch(interpret: Optional[bool]):
    """flash_attention's dispatch rule: None -> Pallas on TPU, jnp fallback
    elsewhere; True forces the Pallas interpreter (tests)."""
    if interpret is None:
        if jax.default_backend() not in ("tpu", "axon"):
            return None          # identical-math jnp fallback
        return False             # native Pallas
    return bool(interpret)


# --- Public block-form API ------------------------------------------------

def quantize_blocks(xb, interpret: Optional[bool] = None):
    """[nblocks, WIRE_BLOCK] fp32 -> (int8 codes, fp32 [nblocks, 1] scales)."""
    mode = _dispatch(interpret)
    if mode is None:
        return _quantize_blocks_ref(xb)
    return _quantize_blocks_pallas(xb, mode)


def dequantize_blocks(qb, scales, interpret: Optional[bool] = None):
    mode = _dispatch(interpret)
    if mode is None:
        return _dequantize_blocks_ref(qb, scales)
    return _dequantize_blocks_pallas(qb, scales, mode)


def _to_blocks(flat):
    n = flat.shape[0]
    nblocks = max(1, -(-n // WIRE_BLOCK))
    pad = nblocks * WIRE_BLOCK - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(nblocks, WIRE_BLOCK)


def quantize(flat, interpret: Optional[bool] = None):
    """Flat fp32 [n] -> (codes [nblocks, WIRE_BLOCK] int8, scales
    [nblocks, 1] fp32).  The short last block is zero-padded, which cannot
    change its max|x| — identical to the byte codec's short-block rule."""
    return quantize_blocks(_to_blocks(flat.astype(jnp.float32)), interpret)


def dequantize(qb, scales, count: int, interpret: Optional[bool] = None):
    """Inverse of :func:`quantize`: back to flat fp32 [count]."""
    xb = dequantize_blocks(qb, scales, interpret)
    return xb.reshape(-1)[:count]


def fake_quantize(x, interpret: Optional[bool] = None):
    """dequantize(quantize(x)) with x's shape — the local quantization
    image used by error feedback (residual = x - fake_quantize(x))."""
    flat = x.reshape(-1)
    qb, s = quantize(flat, interpret)
    return dequantize(qb, s, flat.shape[0], interpret).reshape(x.shape)
