"""Block-scaled quantization codecs for the device plane (traced/XLA path).

This is the in-``jit`` mirror of the host ring's block-scaled wire codecs
(``cpp/wire_codec.h``): the same block geometry, the same scale rules, and
the same all-zero / non-finite-block handling, so a tensor quantized on the
device plane decodes to exactly the values the host codec would have
produced.  EQuARX (PAPERS.md) is the design reference: block-scaled codes
inside the XLA program keep the compression on-chip — no host transfers —
while fp32 accumulation between hops preserves reduction accuracy.

Three device codecs:

- ``int8``: one fp32 scale per 256-element block, ``scale = max|x| / 127``.
- ``int4``: the same block scale with 4-bit codes packed two per byte
  (``scale = max|x| / WIRE_INT4_MAX``); on the wire this is ~0.13x raw.
- ``int8g``: EQuARX-style two-level scales — one fp32 scale per
  4096-element group (``WIRE_GROUP``) plus one uint8 sub-scale per block:
  ``group scale = max|group|/127``, ``sub = round(max|block|/max|group| *
  WIRE_SUB_DENOM)`` clamped to 255, effective block scale ``= group_scale
  * sub/WIRE_SUB_DENOM``.  The denominator is a power of two (256) so the
  effective scale is bit-stable under any multiply association order —
  every rank recomputing ``eff`` from the same wire bytes gets the same
  bits regardless of how the compiler fuses the expression (a /127
  denominator is 1-ulp sensitive to reassociation, which breaks cross-rank
  bit-identity when encoded payloads are forwarded verbatim).  Per-block
  granularity at ~1/4 of int8's scale overhead.

Layout: a flat fp32 tensor is viewed as ``[nblocks, WIRE_BLOCK]`` (the last
block zero-padded; zeros cannot raise ``max|x|``, so a short last block
quantizes exactly as the byte-stream codec quantizes it).  Quantization
yields a code array plus scales — for int8/int4 one fp32 per block, for
int8g a ``(sub, group_scale)`` pair — together the traced analog of the
wire stream's records, and what actually rides ``lax.ppermute`` between
devices.

The kernels are Pallas with the same dispatch rules as
``ops/flash_attention.py``: on TPU the Pallas kernel runs natively,
off-TPU the public entry points fall back to an identical-math jnp
implementation, and ``interpret=True`` forces the kernels through the
Pallas interpreter (tests).  Scale/inv divides are computed OUTSIDE the
kernels (XLA's fp32 divide is correctly rounded, matching the C++ side;
the Pallas interpreter's is not), and the int4 nibble pack/unpack is exact
integer math in plain jnp.

Byte accounting: every quantized collective calls :func:`note_device_bytes`
with the raw-vs-encoded wire byte counts so the realized compression ratio
is observable (``data_plane_stats()['device_raw'/'device_encoded']``,
``hvd.metrics()``, Prometheus).
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# --- Block geometry and codec ids: MUST mirror cpp/wire_codec.h ----------
# (tools/hvd_lint.py's wire-codec pass checks these against the header; a
# drift fails lint.)
WIRE_BLOCK = 256           # kWireBlock: elements per scale record
WIRE_SCALE_BYTES = 4       # kWireScaleBytes: little-endian fp32 scale
WIRE_GROUP = 4096          # kWireGroup: elements per int8g group scale
WIRE_INT4_MAX = 7          # kWireInt4Max: int4 code clamp bound
WIRE_SUB_DENOM = 256       # kWireSubDenom: int8g sub-scale denominator (2^8)
WIRE_CODEC_IDS = {"none": 0, "bf16": 1, "int8": 2, "int4": 3, "int8g": 4}
# Codecs the device plane can engage.  bf16 stays host-only: on-chip the
# bf16 cast is a plain convert_element_type XLA already fuses — only the
# block-scaled codecs need an implementation here.
DEVICE_WIRE_CODECS = ("none", "int8", "int4", "int8g")

_BLOCKS_PER_GROUP = WIRE_GROUP // WIRE_BLOCK   # int8g sub-scales per group

# Rows per Pallas grid step: 32 sublanes satisfies the int8 (32, 128) and
# fp32 (8, 128) minimum tile constraints simultaneously (WIRE_BLOCK = 256
# lanes is a multiple of 128).
_QUANT_ROWS = 32


def encoded_nbytes(count: int, codec: str = "int8") -> int:
    """Wire bytes for ``count`` fp32 elements under ``codec`` — the same
    formula as WireEncodedBytes."""
    count = int(count)
    blocks = -(-count // WIRE_BLOCK)
    if codec == "none":
        return 4 * count
    if codec == "bf16":
        return 2 * count
    if codec == "int4":
        return blocks * WIRE_SCALE_BYTES + (count + 1) // 2
    if codec == "int8g":
        groups = -(-count // WIRE_GROUP)
        return groups * WIRE_SCALE_BYTES + blocks + count
    return blocks * WIRE_SCALE_BYTES + count


def torus_factors(world: int) -> Optional[Tuple[int, int]]:
    """Near-square 2-D factorization ``(a, b)`` of ``world`` with
    ``2 <= a <= b`` and ``a`` maximal (a = major/outer axis, b =
    minor/inner axis).  None when ``world`` is prime or < 4 — the torus
    schedule then demotes to a 1-D ring."""
    world = int(world)
    if world < 4:
        return None
    a = int(math.isqrt(world))
    while a >= 2:
        if world % a == 0:
            return (a, world // a)
        a -= 1
    return None


def ring_bytes(count: int, world: int, codec: str = "int8",
               schedule: str = "ring") -> Tuple[int, int]:
    """Per-rank (raw, encoded) wire bytes for one quantized allreduce of
    ``count`` fp32 elements over ``world`` ranks under ``schedule``:

    - ``ring``: reduce-scatter plus all-gather, world-1 hops each, one
      chunk of ``ceil(count/world)`` elements per hop.
    - ``bidi``: same hop count but each hop carries two half chunks, one
      per ICI direction, so per-link bytes per hop halve (totals per rank
      are schedule-identical up to short-block scale overhead).
    - ``torus`` (a x b factorization): 2(b-1) hops of ``ceil(count/b)``
      along the minor axis plus 2(a-1) hops of ``ceil(ceil(count/b)/a)``
      along the major axis — O(a+b) chunk-hops instead of O(ab).
    """
    world = max(1, int(world))
    count = int(count)
    if world == 1:
        return (0, 0)
    if schedule == "torus":
        f = torus_factors(world)
        if f is not None:
            a, b = f
            c1 = -(-count // b)
            c2 = -(-c1 // a)
            h1 = 2 * (b - 1)
            h2 = 2 * (a - 1)
            return (4 * (h1 * c1 + h2 * c2),
                    h1 * encoded_nbytes(c1, codec) +
                    h2 * encoded_nbytes(c2, codec))
        schedule = "bidi"          # prime/small world: torus -> bidi
    chunk = -(-count // world)
    hops = 2 * (world - 1)
    if schedule == "bidi" and chunk >= 2:
        front = chunk // 2
        back = chunk - front
        return (hops * chunk * 4,
                hops * (encoded_nbytes(front, codec) +
                        encoded_nbytes(back, codec)))
    return (hops * chunk * 4, hops * encoded_nbytes(chunk, codec))


# --- Device-plane byte counters ------------------------------------------

_DEV_LOCK = threading.Lock()
_DEV_RAW = 0
_DEV_ENCODED = 0
_NATIVE_SINK: Optional[Callable[[int, int], None]] = None


def set_native_byte_sink(fn: Optional[Callable[[int, int], None]]) -> None:
    """Register a callable forwarding (raw, encoded) deltas to the native
    metrics registry (NativeCore wires hvd_device_plane_note here) so the
    counters show up in hvd.metrics() / Prometheus."""
    global _NATIVE_SINK
    _NATIVE_SINK = fn


def note_device_bytes(raw: int, encoded: int) -> None:
    global _DEV_RAW, _DEV_ENCODED
    with _DEV_LOCK:
        _DEV_RAW += int(raw)
        _DEV_ENCODED += int(encoded)
    sink = _NATIVE_SINK
    if sink is not None:
        try:
            sink(int(raw), int(encoded))
        except Exception:
            pass


def device_byte_counters() -> Tuple[int, int]:
    with _DEV_LOCK:
        return (_DEV_RAW, _DEV_ENCODED)


def reset_device_byte_counters() -> None:
    global _DEV_RAW, _DEV_ENCODED
    with _DEV_LOCK:
        _DEV_RAW = 0
        _DEV_ENCODED = 0


# --- Block-form reference implementation (identical math to WireEncode) --

def _block_scales(xb, qmax: float = 127.0):
    """Per-block (scale, inv) mirroring WireEncode(kInt8/kInt4)
    bit-for-bit:

    - max|x| scans with ``a > maxabs`` so NaN elements never win the max
      (an all-NaN block keeps scale 0 and encodes zeros);
    - a block whose max is inf gets a non-finite scale -> codes all zero
      (the stored scale stays inf, so decode flags the block as NaN rather
      than inventing values).

    ``inv`` is 0 exactly for the all-zero / non-finite blocks (a finite
    positive scale can never reciprocate to 0 in fp32), so ``inv > 0`` is
    the block-ok predicate downstream.  Computed in plain jnp — XLA's
    fp32 divide is correctly rounded, matching the C++ divides; the Pallas
    interpreter's is not, which is why the divides live outside the kernel.
    """
    absx = jnp.abs(xb)
    maxabs = jnp.max(jnp.where(jnp.isnan(absx), 0.0, absx),
                     axis=1, keepdims=True)
    scale = maxabs / qmax
    ok = (scale > 0.0) & jnp.isfinite(scale)
    inv = jnp.where(ok, 1.0 / jnp.where(ok, scale, 1.0), 0.0)
    return scale.astype(jnp.float32), inv.astype(jnp.float32)


def _group_scales(xb):
    """Two-level (int8g) scale derivation mirroring WireEncode(kInt8g):

    - group max = max over the group's block maxes (fp32 max is exact, so
      this equals the C++ single-pass group scan, NaN-excluded alike);
    - ``gscale = gmax / 127``; a zero or non-finite group stores sub-scale
      bytes 0 and codes 0 (non-finite keeps gscale inf, so decode flags
      the group as NaN via inf * 0, exactly like the single-level codecs);
    - per block ``sub = round(bmax/gmax * WIRE_SUB_DENOM)`` clamped to
      [0, 255] (the block holding gmax rounds to 256 and clamps), effective
      scale ``eff = gscale * (sub/WIRE_SUB_DENOM)``.  The power-of-two
      denominator makes ``eff`` association-order-independent — multiplying
      by 2^-8 commutes exactly with fp32 rounding — so the C++ decoder and
      every XLA fusion of the traced decoder reproduce the encoder's eff
      bit-for-bit.

    Returns (sub [nb,1] uint8, gscale [ng,1] fp32, inv [nb,1] fp32) where
    ``inv`` is 1/eff for ok blocks and 0 otherwise.
    """
    nb = xb.shape[0]
    ng = -(-nb // _BLOCKS_PER_GROUP)
    absx = jnp.abs(xb)
    bmax = jnp.max(jnp.where(jnp.isnan(absx), 0.0, absx),
                   axis=1, keepdims=True)
    pad = ng * _BLOCKS_PER_GROUP - nb
    bmax_p = jnp.pad(bmax, ((0, pad), (0, 0)))
    gmax = jnp.max(bmax_p.reshape(ng, _BLOCKS_PER_GROUP), axis=1,
                   keepdims=True)
    gscale = (gmax / 127.0).astype(jnp.float32)
    gok = (gscale > 0.0) & jnp.isfinite(gscale)

    def rep(a):
        return jnp.repeat(a, _BLOCKS_PER_GROUP, axis=0)[:nb]

    gmax_b, gok_b, gscale_b = rep(gmax), rep(gok), rep(gscale)
    ratio = bmax / jnp.where(gok_b, gmax_b, 1.0)
    sub_f = jnp.where(gok_b,
                      jnp.minimum(jnp.round(ratio * float(WIRE_SUB_DENOM)),
                                  255.0),
                      0.0)
    eff = gscale_b * (sub_f / float(WIRE_SUB_DENOM))
    ok = gok_b & (sub_f > 0.0)
    inv = jnp.where(ok, 1.0 / jnp.where(ok, eff, 1.0), 0.0)
    return (sub_f.astype(jnp.uint8), gscale.astype(jnp.float32),
            inv.astype(jnp.float32))


def _effective_scales(sub, gscale, nblocks: int):
    """Per-block effective fp32 scale from int8g (sub, group) scales —
    the decoder's ``gscale * (sub/WIRE_SUB_DENOM)``, bit-identical to the
    encode-side ``eff``: sub is an exact small integer and the denominator
    is a power of two, so whether the compiler evaluates
    ``(gscale*sub)/256`` or ``gscale*(sub/256)`` the result carries the
    same bits (scaling by 2^-8 commutes exactly with fp32 rounding).
    Decode runs both on a rank's own fresh payload and on ppermute'd
    copies of the same bytes; with a non-power-of-two denominator XLA's
    per-fusion-context codegen produced 1-ulp drift between those two
    sites, breaking the cross-rank bit-identity the verbatim-forwarding
    gather relies on."""
    gs_b = jnp.repeat(gscale.astype(jnp.float32), _BLOCKS_PER_GROUP,
                      axis=0)[:nblocks]
    return gs_b * (sub.astype(jnp.float32) / float(WIRE_SUB_DENOM))


def _quantize_codes_ref(xb, inv, qmax: float = 127.0):
    """Elementwise half of WireEncode: round, clamp, block gate.

    Clamping uses std::min/std::max operand order, under which a NaN
    element inside an otherwise-finite block lands on +qmax (exactly what
    the C++ loop produces)."""
    v = jnp.round(xb * inv)
    v = jnp.where(v < qmax, v, qmax)        # std::min(qmax, v): NaN -> qmax
    v = jnp.where(v > -qmax, v, -qmax)      # std::max(-qmax, v)
    return jnp.where(inv > 0.0, v, 0.0).astype(jnp.int8)


def _quantize_blocks_ref(xb):
    """jnp mirror of WireEncode(kInt8) on [nblocks, WIRE_BLOCK] fp32."""
    scale, inv = _block_scales(xb)
    return _quantize_codes_ref(xb, inv), scale


def _dequantize_blocks_ref(qb, scales):
    """jnp mirror of WireDecodeRange: scale * code, in fp32."""
    return scales.astype(jnp.float32) * qb.astype(jnp.float32)


# --- int4 nibble packing (exact integer jnp, shared by every backend) -----

def _pack_int4(codes):
    """[nblocks, WIRE_BLOCK] int8 codes in [-7, 7] -> [nblocks,
    WIRE_BLOCK/2] packed bytes: element 2i in the low nibble, 2i+1 in the
    high nibble, all arithmetic on uint8 (mod-256, matching the C++
    encoder's unsigned pack)."""
    u = codes.astype(jnp.uint8)
    lo = u[:, 0::2] & 0x0F
    hi = u[:, 1::2] & 0x0F
    return (lo | (hi << 4)).astype(jnp.int8)


def _unpack_int4(packed):
    """Inverse of :func:`_pack_int4`: sign-extend each nibble via the
    ``(nib ^ 8) - 8`` trick (identical to WireDecodeRange(kInt4))."""
    b = packed.astype(jnp.uint8).astype(jnp.int32)
    lo = ((b & 0x0F) ^ 8) - 8
    hi = (((b >> 4) & 0x0F) ^ 8) - 8
    nb = packed.shape[0]
    return jnp.stack([lo, hi], axis=-1).reshape(nb, WIRE_BLOCK).astype(
        jnp.int8)


# --- Pallas kernels -------------------------------------------------------

def _quant_kernel(x_ref, inv_ref, q_ref):
    # Elementwise only (mul/round/compare/select are exactly rounded on
    # every backend, so interpret mode is bit-identical to the jnp
    # fallback); the per-block scale/inv reduction rides in from jnp.
    x = x_ref[...]                                    # [ROWS, WIRE_BLOCK]
    inv = inv_ref[...]                                # [ROWS, 1]
    v = jnp.round(x * inv)
    v = jnp.where(v < 127.0, v, 127.0)
    v = jnp.where(v > -127.0, v, -127.0)
    q_ref[...] = jnp.where(inv > 0.0, v, 0.0).astype(jnp.int8)


def _quant_kernel_int4(x_ref, inv_ref, q_ref):
    qmax = float(WIRE_INT4_MAX)
    x = x_ref[...]
    inv = inv_ref[...]
    v = jnp.round(x * inv)
    v = jnp.where(v < qmax, v, qmax)
    v = jnp.where(v > -qmax, v, -qmax)
    q_ref[...] = jnp.where(inv > 0.0, v, 0.0).astype(jnp.int8)


def _dequant_kernel(q_ref, s_ref, x_ref):
    x_ref[...] = s_ref[...] * q_ref[...].astype(jnp.float32)


def _pad_rows(xb, rows: int):
    nb = xb.shape[0]
    nb_pad = -(-nb // rows) * rows
    if nb_pad != nb:
        xb = jnp.pad(xb, ((0, nb_pad - nb), (0, 0)))
    return xb, nb


def _quantize_codes_pallas(xb, inv, interpret: bool, qmax: float):
    xb, nb = _pad_rows(xb, _QUANT_ROWS)
    inv_p, _ = _pad_rows(inv, _QUANT_ROWS)
    grid = (xb.shape[0] // _QUANT_ROWS,)
    kernel = _quant_kernel if qmax == 127.0 else _quant_kernel_int4
    q = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((_QUANT_ROWS, WIRE_BLOCK), lambda i: (i, 0)),
                  pl.BlockSpec((_QUANT_ROWS, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_QUANT_ROWS, WIRE_BLOCK), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((xb.shape[0], WIRE_BLOCK), jnp.int8),
        interpret=interpret,
    )(xb, inv_p)
    return q[:nb]


def _quantize_blocks_pallas(xb, interpret: bool):
    scale, inv = _block_scales(xb)
    return _quantize_codes_pallas(xb, inv, interpret, 127.0), scale


def _dequantize_blocks_pallas(qb, scales, interpret: bool):
    qb, nb = _pad_rows(qb, _QUANT_ROWS)
    scales, _ = _pad_rows(scales, _QUANT_ROWS)
    grid = (qb.shape[0] // _QUANT_ROWS,)
    x = pl.pallas_call(
        _dequant_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((_QUANT_ROWS, WIRE_BLOCK), lambda i: (i, 0)),
                  pl.BlockSpec((_QUANT_ROWS, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_QUANT_ROWS, WIRE_BLOCK), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((qb.shape[0], WIRE_BLOCK),
                                       jnp.float32),
        interpret=interpret,
    )(qb, scales)
    return x[:nb]


def _dispatch(interpret: Optional[bool]):
    """flash_attention's dispatch rule: None -> Pallas on TPU, jnp fallback
    elsewhere; True forces the Pallas interpreter (tests)."""
    if interpret is None:
        if jax.default_backend() not in ("tpu", "axon"):
            return None          # identical-math jnp fallback
        return False             # native Pallas
    return bool(interpret)


# --- Public block-form API ------------------------------------------------

def quantize_blocks(xb, interpret: Optional[bool] = None):
    """[nblocks, WIRE_BLOCK] fp32 -> (int8 codes, fp32 [nblocks, 1] scales)."""
    mode = _dispatch(interpret)
    if mode is None:
        return _quantize_blocks_ref(xb)
    return _quantize_blocks_pallas(xb, mode)


def dequantize_blocks(qb, scales, interpret: Optional[bool] = None):
    mode = _dispatch(interpret)
    if mode is None:
        return _dequantize_blocks_ref(qb, scales)
    return _dequantize_blocks_pallas(qb, scales, mode)


def _to_blocks(flat):
    n = flat.shape[0]
    nblocks = max(1, -(-n // WIRE_BLOCK))
    pad = nblocks * WIRE_BLOCK - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(nblocks, WIRE_BLOCK)


def quantize(flat, codec: str = "int8", interpret: Optional[bool] = None):
    """Flat fp32 [n] -> (codes, scales) under ``codec``:

    - ``int8``: codes [nblocks, WIRE_BLOCK] int8, scales [nblocks, 1] fp32.
    - ``int4``: codes [nblocks, WIRE_BLOCK/2] int8 (packed nibbles),
      scales [nblocks, 1] fp32.
    - ``int8g``: codes [nblocks, WIRE_BLOCK] int8, scales = (sub
      [nblocks, 1] uint8, group [ngroups, 1] fp32).

    The short last block is zero-padded, which cannot change its max|x| —
    identical to the byte codec's short-block rule.  The (codes, scales)
    pair is a pytree of same-shape-per-rank arrays, so collectives move it
    with ``tree_map``'d ``lax.ppermute``/``all_gather``.
    """
    xb = _to_blocks(flat.astype(jnp.float32))
    mode = _dispatch(interpret)
    if codec == "int4":
        scale, inv = _block_scales(xb, float(WIRE_INT4_MAX))
        if mode is None:
            codes = _quantize_codes_ref(xb, inv, float(WIRE_INT4_MAX))
        else:
            codes = _quantize_codes_pallas(xb, inv, mode,
                                           float(WIRE_INT4_MAX))
        return _pack_int4(codes), scale
    if codec == "int8g":
        sub, gscale, inv = _group_scales(xb)
        if mode is None:
            codes = _quantize_codes_ref(xb, inv)
        else:
            codes = _quantize_codes_pallas(xb, inv, mode, 127.0)
        return codes, (sub, gscale)
    if mode is None:
        return _quantize_blocks_ref(xb)
    return _quantize_blocks_pallas(xb, mode)


def dequantize(qb, scales, count: int, codec: str = "int8",
               interpret: Optional[bool] = None):
    """Inverse of :func:`quantize`: back to flat fp32 [count]."""
    if codec == "int4":
        qb = _unpack_int4(qb)
    elif codec == "int8g":
        sub, gscale = scales
        scales = _effective_scales(sub, gscale, qb.shape[0])
    xb = dequantize_blocks(qb, scales, interpret)
    return xb.reshape(-1)[:count]


def fake_quantize(x, codec: str = "int8",
                  interpret: Optional[bool] = None):
    """dequantize(quantize(x)) with x's shape — the local quantization
    image used by error feedback (residual = x - fake_quantize(x))."""
    flat = x.reshape(-1)
    qb, s = quantize(flat, codec, interpret)
    return dequantize(qb, s, flat.shape[0], codec, interpret).reshape(x.shape)
