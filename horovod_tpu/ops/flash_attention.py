"""Flash attention as a Pallas TPU kernel.

The reference's only custom kernels are CUDA memcpy/scale helpers
(horovod/common/ops/cuda/cuda_kernels.cu; SURVEY.md §2.2) — its models come
from torch/TF.  This framework owns its model zoo, so the hot op worth a
hand kernel on TPU is attention: this kernel keeps the [S, S] score matrix
out of HBM entirely (VMEM-blocked online softmax), the classic
flash-attention trade.

Layout: inputs [batch, seq, heads, head_dim]; the kernel runs on
[batch*heads, seq, head_dim] with a (BH, seq/block_q) grid; K/V live in
VMEM whole (fine to ~8k sequence at head_dim 64-128), Q is blocked.
Causal mode requires block_q == block_k and skips blocks above the
diagonal, so every processed row has at least one valid key (keeps the
online-softmax max finite with a -1e30 mask value, no NaN guards needed).

Off-TPU (CPU tests) the public wrapper falls back to an identical-math
dense implementation; the kernel itself is unit-tested in interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _mha_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale: float,
                causal: bool, block_q: int, block_k: int, valid_len: int):
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * sm_scale          # [Bq, D]
    seq_len = k_ref.shape[0]
    d = q_ref.shape[-1]

    if causal:
        n_blocks = iq + 1                                # skip above-diagonal
    else:
        n_blocks = seq_len // block_k
    padded = valid_len < seq_len

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(                          # [Bq, Bk] on MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal or padded:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                # Padding lives at the tail, so kpos > any real qpos —
                # the causal mask already excludes padded keys.
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, _, l = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_bhsd(qb, kb, vb, sm_scale, causal, block_q, block_k, interpret,
                valid_len):
    """Kernel entry over [BH, S, D] (S already padded to the block size)."""
    bh, s, d = qb.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(_mha_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               valid_len=valid_len)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), qb.dtype),
        interpret=interpret,
    )(qb, kb, vb)


def dense_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Reference-math dense attention over [B, S, H, D] (fp32 softmax)."""
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Attention over [batch, seq, heads, head_dim].

    On TPU this is the Pallas kernel; elsewhere it falls back to the dense
    implementation (identical math) unless ``interpret=True`` forces the
    kernel through the Pallas interpreter (tests).
    """
    b, s, h, d = q.shape
    if interpret is None:
        if jax.default_backend() not in ("tpu", "axon"):
            return dense_attention(q, k, v, causal, scale)
        interpret = False
    sm_scale = d ** -0.5 if scale is None else scale
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if causal and block_q != block_k:
        block_q = block_k = min(block_q, block_k)
    # Pad the sequence up to a multiple of BOTH block sizes (the q grid and
    # the kv loop must each tile s_pad exactly), masking tail keys
    # in-kernel; a dense fallback here would materialize the [S, S] scores
    # this kernel exists to avoid.
    import math

    block = math.lcm(block_q, block_k)
    s_pad = -(-s // block) * block
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)

    out = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), sm_scale, causal,
                      block_q, block_k, bool(interpret), valid_len=s)
    out = out.reshape(b, h, s_pad, d).transpose(0, 2, 1, 3)
    return out[:, :s]
