"""Flash attention as a Pallas TPU kernel.

The reference's only custom kernels are CUDA memcpy/scale helpers
(horovod/common/ops/cuda/cuda_kernels.cu; SURVEY.md §2.2) — its models come
from torch/TF.  This framework owns its model zoo, so the hot op worth a
hand kernel on TPU is attention: this kernel keeps the [S, S] score matrix
out of HBM entirely (VMEM-blocked online softmax), the classic
flash-attention trade.

Layout: inputs [batch, seq, heads, head_dim]; the kernel runs on
[batch*heads, seq, head_dim] with a (BH, seq/block_q) grid; K/V live in
VMEM whole (fine to ~8k sequence at head_dim 64-128), Q is blocked.
Causal mode requires block_q == block_k and skips blocks above the
diagonal, so every processed row has at least one valid key (keeps the
online-softmax max finite with a -1e30 mask value, no NaN guards needed).

Off-TPU (CPU tests) the public wrapper falls back to an identical-math
dense implementation; the kernel itself is unit-tested in interpret mode.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

# Per-row statistics (lse, delta) cross the pallas_call boundary broadcast
# across a trailing 128-lane dimension: the TPU lowering requires the last
# two block dims to be (sublane-multiple, lane-multiple-or-whole), so a
# [rows] vector must ride as [rows, 128] (the same layout the reference
# jax TPU kernel uses for its l/m outputs, MIN_BLOCK_SIZE lanes).  Inside
# kernels the [:, :1] column is the value; wrappers squeeze lane 0.
LANES = 128


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct for a pallas_call output, carrying the varying-
    manual-axes type of ``like`` so the kernel can run inside shard_map
    (check_vma requires outputs to declare their mesh-axis variance)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax without the vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)


def _mha_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale: float,
                causal: bool, block_q: int, block_k: int, valid_len: int):
    iq = pl.program_id(1)
    # Dots run on the MXU in the input dtype (bf16 native rate, 2x the f32
    # path) with f32 accumulation; softmax math stays f32.  The sm_scale is
    # folded in after the QK dot so it happens in f32.
    q = q_ref[:]                                         # [Bq, D]
    seq_len = k_ref.shape[0]
    d = q_ref.shape[-1]

    if causal:
        n_blocks = iq + 1                                # skip above-diagonal
    else:
        n_blocks = seq_len // block_k
    padded = valid_len < seq_len

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(                          # [Bq, Bk] on MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                # Padding lives at the tail, so kpos > any real qpos —
                # the causal mask already excludes padded keys.
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    # Carries derive from q (not fresh constants) so they inherit its
    # varying-manual-axes type when the kernel runs in interpret mode
    # inside shard_map; on real TPU these are the same zeros.
    acc0 = (q * 0).astype(jnp.float32)
    m0 = (q[:, :1] * 0).astype(jnp.float32) + NEG_INF
    l0 = (q[:, :1] * 0).astype(jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # Log-sum-exp per query row, the residual the backward pass needs to
    # re-materialize P = exp(S - lse) blockwise without storing [S, S].
    # Written lane-broadcast ([Bq, LANES]) per the TPU block-shape rule.
    lse_ref[:] = jnp.broadcast_to(
        m + jnp.log(jnp.maximum(l, 1e-30)), (block_q, LANES))


def _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                    interpret, valid_len):
    """Forward kernel over [BH, S, D] (S already padded): out + row lse."""
    bh, s, d = qb.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(_mha_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               valid_len=valid_len)
    out, lse_lanes = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((bh, s, d), qb.dtype, qb),
            _out_struct((bh, s, LANES), jnp.float32, qb),
        ],
        interpret=interpret,
    )(qb, kb, vb)
    return out, lse_lanes[:, :, 0]


def _mha_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, *, sm_scale: float, causal: bool,
                       block_q: int, block_k: int, valid_len: int):
    """dQ for one query block: loop over key blocks, re-materialize P."""
    iq = pl.program_id(1)
    q = q_ref[:]                                           # [Bq, D] bf16/f32
    do = do_ref[:].astype(jnp.float32)                     # [Bq, D]
    lse = lse_ref[:][:, :1]                                # [Bq, 1] f32
    delta = delta_ref[:][:, :1]                            # [Bq, 1] f32
    seq_len = k_ref.shape[0]
    n_blocks = (iq + 1) if causal else seq_len // block_k
    padded = valid_len < seq_len

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [Bq, Bk]
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale                   # [Bq, Bk]
        return dq + jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, n_blocks, body, (q * 0).astype(jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _mha_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dk_ref, dv_ref, *, sm_scale: float, causal: bool,
                        block_q: int, block_k: int, valid_len: int):
    """dK/dV for one key block: loop over query blocks."""
    jk = pl.program_id(1)
    k = k_ref[:]                                           # [Bk, D]
    v = v_ref[:]                                           # [Bk, D]
    seq_len = q_ref.shape[0]
    n_q_blocks = seq_len // block_q
    start = jk * block_k // block_q if causal else 0       # skip above diag
    padded = valid_len < seq_len
    d = q_ref.shape[-1]

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :][:, :1]
        delta = delta_ref[pl.ds(i * block_q, block_q), :][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [Bq, Bk]
        dv = dv + jax.lax.dot_general(                     # P^T @ dO
            p.astype(do_ref.dtype), do.astype(do_ref.dtype),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(                     # dS^T @ Q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = (k * 0).astype(jnp.float32)
    dv0 = (v * 0).astype(jnp.float32)
    dk, dv = jax.lax.fori_loop(start, n_q_blocks, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_bwd_bhsd(qb, kb, vb, ob, lse, dob, sm_scale, causal, block_q,
                    block_k, interpret, valid_len, dlse=None):
    bh, s, d = qb.shape
    # delta_i = rowsum(dO_i * O_i) — the standard backward residual.  An
    # lse cotangent (pair-valued VJP) folds in as delta - dlse.
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)                               # [BH, S]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    # Per-row stats enter the kernels lane-broadcast (see LANES).
    lse_l = jnp.broadcast_to(lse.astype(jnp.float32)[..., None],
                             (bh, s, LANES))
    delta_l = jnp.broadcast_to(delta[..., None], (bh, s, LANES))
    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, valid_len=valid_len)
    qspec = pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0))
    kspec = pl.BlockSpec((None, block_k, d), lambda b, i: (b, i, 0))
    full = pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0))
    row_q = pl.BlockSpec((None, block_q, LANES), lambda b, i: (b, i, 0))
    row_full = pl.BlockSpec((None, s, LANES), lambda b, i: (b, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_mha_bwd_dq_kernel, **common),
        grid=(bh, s // block_q),
        in_specs=[qspec, full, full, qspec, row_q, row_q],
        out_specs=qspec,
        out_shape=_out_struct((bh, s, d), qb.dtype, qb),
        interpret=interpret,
    )(qb, kb, vb, dob, lse_l, delta_l)
    dk, dv = pl.pallas_call(
        functools.partial(_mha_bwd_dkv_kernel, **common),
        grid=(bh, s // block_k),
        in_specs=[full, kspec, kspec, full, row_full, row_full],
        out_specs=[kspec, kspec],
        out_shape=[_out_struct((bh, s, d), kb.dtype, kb),
                   _out_struct((bh, s, d), vb.dtype, vb)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse_l, delta_l)
    return dq, dk, dv




@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd_lse(qb, kb, vb, sm_scale, causal, block_q, block_k,
                    interpret, valid_len):
    """Differentiable kernel entry over [BH, S, D] (S already padded),
    returning ``(out, lse)`` — the pair ring attention merges across hops
    (the public ``flash_attention`` wrapper simply discards the lse).

    The backward for the pair is the standard flash backward with one
    twist: dL/dS_ij gains a ``+ dlse_i * p_ij`` term, which folds into the
    existing kernels as ``delta_i -> delta_i - dlse_i`` (both enter as
    ``ds = p * (dp - delta)``) — no separate kernels needed.
    """
    return _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                           interpret, valid_len)


def _flash_bhsd_lse_fwd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                        interpret, valid_len):
    out, lse = _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q,
                               block_k, interpret, valid_len)
    return (out, lse), (qb, kb, vb, out, lse)


def _flash_bhsd_lse_bwd(sm_scale, causal, block_q, block_k, interpret,
                        valid_len, res, cotangents):
    qb, kb, vb, ob, lse = res
    dob, dlse = cotangents
    dq, dk, dv = _flash_bwd_bhsd(qb, kb, vb, ob, lse, dob, sm_scale, causal,
                                 block_q, block_k, interpret, valid_len,
                                 dlse=dlse)
    return dq, dk, dv


_flash_bhsd_lse.defvjp(_flash_bhsd_lse_fwd, _flash_bhsd_lse_bwd)


# Per-core VMEM by TPU generation (v4/v5e/v5p: 128 MiB, v6e: 128 MiB;
# older v2/v3: 16 MiB/core x2 cores presented as 32).  Half is budgeted for
# K+V, leaving room for the q/out/acc blocks and double-buffering.
_VMEM_BYTES_BY_KIND = (
    ("TPU v6", 128 << 20),
    ("TPU v5", 128 << 20),
    ("TPU v4", 128 << 20),
    ("TPU v3", 32 << 20),
    ("TPU v2", 32 << 20),
)


def _kv_vmem_budget() -> int:
    env = os.environ.get("HVD_TPU_FLASH_VMEM_BUDGET_MB")
    if env:
        try:
            budget = int(env)
        except ValueError:
            budget = 0
        if budget <= 0:
            raise ValueError(
                f"HVD_TPU_FLASH_VMEM_BUDGET_MB must be a positive integer "
                f"MiB count, got {env!r}")
        return budget << 20
    try:
        kind = jax.devices()[0].device_kind
        for prefix, vmem in _VMEM_BYTES_BY_KIND:
            if kind.startswith(prefix):
                return vmem // 2
    except Exception:
        pass
    return 64 << 20  # conservative default: v4/v5-class half-VMEM


def _check_kv_vmem(s: int, d: int, dtype) -> None:
    # K and V live whole in VMEM (bandwidth-optimal: fetched once, not once
    # per query block).  That caps the per-device sequence length; beyond
    # it, shard the sequence instead (parallel.ring_attention on an sp
    # axis, whose per-hop chunks come back under the cap).
    budget = _kv_vmem_budget()
    kv_bytes = 2 * s * d * jnp.dtype(dtype).itemsize
    if kv_bytes > budget:
        raise ValueError(
            f"flash_attention: K+V for seq_len={s}, head_dim={d} need "
            f"{kv_bytes / 2**20:.0f} MiB of VMEM (>{budget >> 20} MiB "
            "budget; override with HVD_TPU_FLASH_VMEM_BUDGET_MB). Shard "
            "the sequence across devices with "
            "horovod_tpu.parallel.ring_attention instead.")


def dense_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Reference-math dense attention over [B, S, H, D] (fp32 softmax)."""
    out, _ = dense_attention_with_lse(q, k, v, causal, scale)
    return out


def dense_attention_with_lse(q, k, v, causal: bool = False,
                             scale: Optional[float] = None):
    """Dense attention that also returns log-sum-exp [B, H, S] (the chunk
    statistic ring attention merges across hops)."""
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)      # [B, H, S]
    probs = jnp.exp(logits - lse[..., None]).astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return out, lse


def flash_attention_with_lse(q, k, v, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: int = 128, block_k: int = 128,
                             interpret: Optional[bool] = None):
    """Pallas attention over [B, S, H, D] returning ``(out, lse)`` with
    lse shaped [B, H, S].  Same dispatch rules as :func:`flash_attention`;
    off-TPU it falls back to :func:`dense_attention_with_lse`."""
    b, s, h, d = q.shape
    if interpret is None:
        if jax.default_backend() not in ("tpu", "axon"):
            return dense_attention_with_lse(q, k, v, causal, scale)
        interpret = False
    sm_scale = d ** -0.5 if scale is None else scale
    if not interpret:
        # Interpret mode (CPU tests) has no VMEM; only the real TPU
        # lowering is bound by it.
        _check_kv_vmem(s, d, k.dtype)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if causal and block_q != block_k:
        block_q = block_k = min(block_q, block_k)
    import math

    block = math.lcm(block_q, block_k)
    s_pad = -(-s // block) * block
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)

    out, lse = _flash_bhsd_lse(to_bhsd(q), to_bhsd(k), to_bhsd(v), sm_scale,
                               causal, block_q, block_k, bool(interpret), s)
    out = out.reshape(b, h, s_pad, d).transpose(0, 2, 1, 3)[:, :s]
    lse = lse.reshape(b, h, s_pad)[:, :, :s]
    return out, lse


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Attention over [batch, seq, heads, head_dim].

    On TPU this is the Pallas kernel; elsewhere it falls back to the dense
    implementation (identical math) unless ``interpret=True`` forces the
    kernel through the Pallas interpreter (tests).
    """
    out, _ = flash_attention_with_lse(q, k, v, causal, scale, block_q,
                                      block_k, interpret)
    return out
