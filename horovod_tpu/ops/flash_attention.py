"""Flash attention as a Pallas TPU kernel.

The reference's only custom kernels are CUDA memcpy/scale helpers
(horovod/common/ops/cuda/cuda_kernels.cu; SURVEY.md §2.2) — its models come
from torch/TF.  This framework owns its model zoo, so the hot op worth a
hand kernel on TPU is attention: this kernel keeps the [S, S] score matrix
out of HBM entirely (VMEM-blocked online softmax), the classic
flash-attention trade.

Layout: inputs [batch, seq, heads, head_dim]; the kernels run on
[batch*heads, seq, head_dim] with streaming (BH, n_q, n_kv)-style grids:
K/V (forward) or Q/dO (dK/dV backward) blocks flow through VMEM while the
online-softmax state (acc/m/l, or the dq/dk/dv partials) persists in f32
scratch across the innermost grid steps — so no operand is ever VMEM-whole
and sequence length is HBM-bound, not VMEM-bound.  Causal mode requires
block_q == block_k; tiles above the diagonal (and tiles entirely in tail
padding) are predicated off with pl.when, so every processed row has at
least one valid key (keeps the online-softmax max finite with a -1e30 mask
value, no NaN guards needed).

Off-TPU (CPU tests) the public wrapper falls back to an identical-math
dense implementation; the kernels are unit-tested in interpret mode and
validated on hardware by tools/tpu_flash_validate.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Per-row statistics (lse, delta) cross the pallas_call boundary broadcast
# across a trailing 128-lane dimension: the TPU lowering requires the last
# two block dims to be (sublane-multiple, lane-multiple-or-whole), so a
# [rows] vector must ride as [rows, 128] (the same layout the reference
# jax TPU kernel uses for its l/m outputs, MIN_BLOCK_SIZE lanes).  Inside
# kernels the [:, :1] column is the value; wrappers squeeze lane 0.
LANES = 128


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct for a pallas_call output, carrying the varying-
    manual-axes type of ``like`` so the kernel can run inside shard_map
    (check_vma requires outputs to declare their mesh-axis variance)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax without the vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)


def _block_live(iq, jk, causal: bool, block_q: int, block_k: int,
                valid_len: int, seq_len: int):
    """Whether the (q-block iq, k-block jk) tile can contribute: on the TPU
    the grid is sequential and can't be shortened per-row, so dead tiles
    (above the causal diagonal, or entirely in tail padding) are skipped by
    predication — the dots never issue, only the pipelined DMA runs."""
    live = jk * block_k < valid_len
    if causal:
        live = jnp.logical_and(live,
                               (iq + 1) * block_q - 1 >= jk * block_k)
    return live


def _mha_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale: float, causal: bool, block_q: int, block_k: int,
                valid_len: int):
    """Streaming forward: grid (BH, n_q, n_kv), K/V blocks flow through
    VMEM while acc/m/l persist in scratch across the innermost kv steps
    (the o/lse output blocks are revisited and written on the last step)."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    n_kv = pl.num_programs(2)
    seq_len = n_kv * block_k
    padded = valid_len < seq_len

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(_block_live(iq, jk, causal, block_q, block_k, valid_len,
                         seq_len))
    def _compute():
        # Dots run on the MXU in the input dtype (bf16 native rate, 2x the
        # f32 path) with f32 accumulation; softmax math stays f32.  The
        # sm_scale folds in after the QK dot so it happens in f32.
        q = q_ref[:]                                      # [Bq, D]
        k = k_ref[:]                                      # [Bk, D]
        v = v_ref[:]
        s = jax.lax.dot_general(                          # [Bq, Bk] on MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                # Padding lives at the tail, so kpos > any real qpos —
                # the causal mask already excludes padded keys.
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(jk == n_kv - 1)
    def _flush():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[:] = (acc_ref[:] / l).astype(o_ref.dtype)
        # Log-sum-exp per query row, the residual the backward pass needs
        # to re-materialize P = exp(S - lse) blockwise without storing
        # [S, S].  Written lane-broadcast ([Bq, LANES]) per the TPU
        # block-shape rule.
        lse_ref[:] = jnp.broadcast_to(m_ref[:] + jnp.log(l),
                                      (block_q, LANES))


def _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                    interpret, valid_len):
    """Forward kernel over [BH, S, D] (S already padded): out + row lse."""
    bh, s, d = qb.shape
    grid = (bh, s // block_q, s // block_k)
    kernel = functools.partial(_mha_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               valid_len=valid_len)
    out, lse_lanes = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((bh, s, d), qb.dtype, qb),
            _out_struct((bh, s, LANES), jnp.float32, qb),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb)
    return out, lse_lanes[:, :, 0]


def _mha_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, acc_ref, *, sm_scale: float, causal: bool,
                       block_q: int, block_k: int, valid_len: int):
    """dQ, streaming: grid (BH, n_q, n_kv); K/V blocks flow past a fixed
    query block while dq accumulates in f32 scratch (the dq output block is
    revisited and written on the last kv step).  P is re-materialized from
    the lse residual — the [S, S] score matrix never exists."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    n_kv = pl.num_programs(2)
    seq_len = n_kv * block_k
    padded = valid_len < seq_len

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(_block_live(iq, jk, causal, block_q, block_k, valid_len,
                         seq_len))
    def _compute():
        q = q_ref[:]                                       # [Bq, D]
        k = k_ref[:]                                       # [Bk, D]
        v = v_ref[:]
        do = do_ref[:].astype(jnp.float32)                 # [Bq, D]
        lse = lse_ref[:][:, :1]                            # [Bq, 1] f32
        delta = delta_ref[:][:, :1]                        # [Bq, 1] f32
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [Bq, Bk]
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale                   # [Bq, Bk]
        acc_ref[:] = acc_ref[:] + jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

    @pl.when(jk == n_kv - 1)
    def _flush():
        dq_ref[:] = acc_ref[:].astype(dq_ref.dtype)


def _mha_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale: float,
                        causal: bool, block_q: int, block_k: int,
                        valid_len: int):
    """dK/dV, streaming: grid (BH, n_kv, n_q); Q/dO/stat blocks flow past a
    fixed key block while dk/dv accumulate in f32 scratch."""
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    n_q = pl.num_programs(2)
    seq_len = n_q * block_q
    padded = valid_len < seq_len

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_block_live(iq, jk, causal, block_q, block_k, valid_len,
                         seq_len))
    def _compute():
        q = q_ref[:]                                       # [Bq, D]
        k = k_ref[:]                                       # [Bk, D]
        v = v_ref[:]
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:][:, :1]
        delta = delta_ref[:][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [Bq, Bk]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(       # P^T @ dO
            p.astype(do_ref.dtype), do.astype(do_ref.dtype),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(       # dS^T @ Q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == n_q - 1)
    def _flush():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_bhsd(qb, kb, vb, ob, lse, dob, sm_scale, causal, block_q,
                    block_k, interpret, valid_len, dlse=None):
    bh, s, d = qb.shape
    # delta_i = rowsum(dO_i * O_i) — the standard backward residual.  An
    # lse cotangent (pair-valued VJP) folds in as delta - dlse.
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)                               # [BH, S]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    # Per-row stats enter the kernels lane-broadcast (see LANES).
    lse_l = jnp.broadcast_to(lse.astype(jnp.float32)[..., None],
                             (bh, s, LANES))
    delta_l = jnp.broadcast_to(delta[..., None], (bh, s, LANES))
    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, valid_len=valid_len)
    # dq: q-block fixed per outer step, k/v stream on the inner grid dim.
    q_by_i = pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0))
    kv_by_j = pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0))
    row_by_i = pl.BlockSpec((None, block_q, LANES), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_mha_bwd_dq_kernel, **common),
        grid=(bh, s // block_q, s // block_k),
        in_specs=[q_by_i, kv_by_j, kv_by_j, q_by_i, row_by_i, row_by_i],
        out_specs=q_by_i,
        out_shape=_out_struct((bh, s, d), qb.dtype, qb),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse_l, delta_l)
    # dk/dv: k-block fixed per outer step, q/do/stats stream inside.
    q_by_j = pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, j, 0))
    kv_by_i = pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0))
    row_by_j = pl.BlockSpec((None, block_q, LANES), lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_mha_bwd_dkv_kernel, **common),
        grid=(bh, s // block_k, s // block_q),
        in_specs=[q_by_j, kv_by_i, kv_by_i, q_by_j, row_by_j, row_by_j],
        out_specs=[kv_by_i, kv_by_i],
        out_shape=[_out_struct((bh, s, d), kb.dtype, kb),
                   _out_struct((bh, s, d), vb.dtype, vb)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse_l, delta_l)
    return dq, dk, dv




@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd_lse(qb, kb, vb, sm_scale, causal, block_q, block_k,
                    interpret, valid_len):
    """Differentiable kernel entry over [BH, S, D] (S already padded),
    returning ``(out, lse)`` — the pair ring attention merges across hops
    (the public ``flash_attention`` wrapper simply discards the lse).

    The backward for the pair is the standard flash backward with one
    twist: dL/dS_ij gains a ``+ dlse_i * p_ij`` term, which folds into the
    existing kernels as ``delta_i -> delta_i - dlse_i`` (both enter as
    ``ds = p * (dp - delta)``) — no separate kernels needed.
    """
    return _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                           interpret, valid_len)


def _flash_bhsd_lse_fwd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                        interpret, valid_len):
    out, lse = _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q,
                               block_k, interpret, valid_len)
    return (out, lse), (qb, kb, vb, out, lse)


def _flash_bhsd_lse_bwd(sm_scale, causal, block_q, block_k, interpret,
                        valid_len, res, cotangents):
    qb, kb, vb, ob, lse = res
    dob, dlse = cotangents
    dq, dk, dv = _flash_bwd_bhsd(qb, kb, vb, ob, lse, dob, sm_scale, causal,
                                 block_q, block_k, interpret, valid_len,
                                 dlse=dlse)
    return dq, dk, dv


_flash_bhsd_lse.defvjp(_flash_bhsd_lse_fwd, _flash_bhsd_lse_bwd)


def dense_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Reference-math dense attention over [B, S, H, D] (fp32 softmax)."""
    out, _ = dense_attention_with_lse(q, k, v, causal, scale)
    return out


def dense_attention_with_lse(q, k, v, causal: bool = False,
                             scale: Optional[float] = None):
    """Dense attention that also returns log-sum-exp [B, H, S] (the chunk
    statistic ring attention merges across hops)."""
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)      # [B, H, S]
    probs = jnp.exp(logits - lse[..., None]).astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return out, lse


def flash_attention_with_lse(q, k, v, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: int = 128, block_k: int = 128,
                             interpret: Optional[bool] = None):
    """Pallas attention over [B, S, H, D] returning ``(out, lse)`` with
    lse shaped [B, H, S].  Same dispatch rules as :func:`flash_attention`;
    off-TPU it falls back to :func:`dense_attention_with_lse`."""
    b, s, h, d = q.shape
    if interpret is None:
        if jax.default_backend() not in ("tpu", "axon"):
            return dense_attention_with_lse(q, k, v, causal, scale)
        interpret = False
    sm_scale = d ** -0.5 if scale is None else scale
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if causal and block_q != block_k:
        block_q = block_k = min(block_q, block_k)
    import math

    block = math.lcm(block_q, block_k)
    s_pad = -(-s // block) * block
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)

    out, lse = _flash_bhsd_lse(to_bhsd(q), to_bhsd(k), to_bhsd(v), sm_scale,
                               causal, block_q, block_k, bool(interpret), s)
    out = out.reshape(b, h, s_pad, d).transpose(0, 2, 1, 3)[:, :s]
    lse = lse.reshape(b, h, s_pad)[:, :, :s]
    return out, lse


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Attention over [batch, seq, heads, head_dim].

    On TPU this is the Pallas kernel; elsewhere it falls back to the dense
    implementation (identical math) unless ``interpret=True`` forces the
    kernel through the Pallas interpreter (tests).
    """
    out, _ = flash_attention_with_lse(q, k, v, causal, scale, block_q,
                                      block_k, interpret)
    return out
