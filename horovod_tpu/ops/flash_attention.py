"""Flash attention as a Pallas TPU kernel.

The reference's only custom kernels are CUDA memcpy/scale helpers
(horovod/common/ops/cuda/cuda_kernels.cu; SURVEY.md §2.2) — its models come
from torch/TF.  This framework owns its model zoo, so the hot op worth a
hand kernel on TPU is attention: this kernel keeps the [S, S] score matrix
out of HBM entirely (VMEM-blocked online softmax), the classic
flash-attention trade.

Layout: inputs [batch, seq, heads, head_dim]; the kernel runs on
[batch*heads, seq, head_dim] with a (BH, seq/block_q) grid; K/V live in
VMEM whole (fine to ~8k sequence at head_dim 64-128), Q is blocked.
Causal mode requires block_q == block_k and skips blocks above the
diagonal, so every processed row has at least one valid key (keeps the
online-softmax max finite with a -1e30 mask value, no NaN guards needed).

Off-TPU (CPU tests) the public wrapper falls back to an identical-math
dense implementation; the kernel itself is unit-tested in interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _mha_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale: float,
                causal: bool, block_q: int, block_k: int, valid_len: int):
    iq = pl.program_id(1)
    # Dots run on the MXU in the input dtype (bf16 native rate, 2x the f32
    # path) with f32 accumulation; softmax math stays f32.  The sm_scale is
    # folded in after the QK dot so it happens in f32.
    q = q_ref[:]                                         # [Bq, D]
    seq_len = k_ref.shape[0]
    d = q_ref.shape[-1]

    if causal:
        n_blocks = iq + 1                                # skip above-diagonal
    else:
        n_blocks = seq_len // block_k
    padded = valid_len < seq_len

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(                          # [Bq, Bk] on MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                # Padding lives at the tail, so kpos > any real qpos —
                # the causal mask already excludes padded keys.
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # Log-sum-exp per query row, the residual the backward pass needs to
    # re-materialize P = exp(S - lse) blockwise without storing [S, S].
    lse_ref[:] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, 0]


def _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                    interpret, valid_len):
    """Forward kernel over [BH, S, D] (S already padded): out + row lse."""
    bh, s, d = qb.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(_mha_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               valid_len=valid_len)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), qb.dtype),
            jax.ShapeDtypeStruct((bh, s), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb)


def _mha_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, *, sm_scale: float, causal: bool,
                       block_q: int, block_k: int, valid_len: int):
    """dQ for one query block: loop over key blocks, re-materialize P."""
    iq = pl.program_id(1)
    q = q_ref[:]                                           # [Bq, D] bf16/f32
    do = do_ref[:].astype(jnp.float32)                     # [Bq, D]
    lse = lse_ref[:][:, None]                              # [Bq, 1] f32
    delta = delta_ref[:][:, None]                          # [Bq, 1] f32
    seq_len = k_ref.shape[0]
    n_blocks = (iq + 1) if causal else seq_len // block_k
    padded = valid_len < seq_len

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [Bq, Bk]
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale                   # [Bq, Bk]
        return dq + jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, n_blocks, body, jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _mha_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dk_ref, dv_ref, *, sm_scale: float, causal: bool,
                        block_q: int, block_k: int, valid_len: int):
    """dK/dV for one key block: loop over query blocks."""
    jk = pl.program_id(1)
    k = k_ref[:]                                           # [Bk, D]
    v = v_ref[:]                                           # [Bk, D]
    seq_len = q_ref.shape[0]
    n_q_blocks = seq_len // block_q
    start = jk * block_k // block_q if causal else 0       # skip above diag
    padded = valid_len < seq_len
    d = q_ref.shape[-1]

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[pl.ds(i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal or padded:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            else:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [Bq, Bk]
        dv = dv + jax.lax.dot_general(                     # P^T @ dO
            p.astype(do_ref.dtype), do.astype(do_ref.dtype),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(                     # dS^T @ Q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, n_q_blocks, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_bwd_bhsd(qb, kb, vb, ob, lse, dob, sm_scale, causal, block_q,
                    block_k, interpret, valid_len):
    bh, s, d = qb.shape
    # delta_i = rowsum(dO_i * O_i) — the standard backward residual.
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)                               # [BH, S]
    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, valid_len=valid_len)
    qspec = pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0))
    kspec = pl.BlockSpec((None, block_k, d), lambda b, i: (b, i, 0))
    full = pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0))
    row_q = pl.BlockSpec((None, block_q), lambda b, i: (b, i))
    row_full = pl.BlockSpec((None, s), lambda b, i: (b, 0))
    dq = pl.pallas_call(
        functools.partial(_mha_bwd_dq_kernel, **common),
        grid=(bh, s // block_q),
        in_specs=[qspec, full, full, qspec, row_q, row_q],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), qb.dtype),
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_mha_bwd_dkv_kernel, **common),
        grid=(bh, s // block_k),
        in_specs=[full, kspec, kspec, full, row_full, row_full],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), kb.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), vb.dtype)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd(qb, kb, vb, sm_scale, causal, block_q, block_k, interpret,
                valid_len):
    """Differentiable kernel entry over [BH, S, D] (S already padded)."""
    out, _ = _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                             interpret, valid_len)
    return out


def _flash_bhsd_fwd(qb, kb, vb, sm_scale, causal, block_q, block_k,
                    interpret, valid_len):
    out, lse = _flash_fwd_bhsd(qb, kb, vb, sm_scale, causal, block_q,
                               block_k, interpret, valid_len)
    return out, (qb, kb, vb, out, lse)


def _flash_bhsd_bwd(sm_scale, causal, block_q, block_k, interpret, valid_len,
                    res, dob):
    qb, kb, vb, ob, lse = res
    dq, dk, dv = _flash_bwd_bhsd(qb, kb, vb, ob, lse, dob, sm_scale, causal,
                                 block_q, block_k, interpret, valid_len)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def dense_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Reference-math dense attention over [B, S, H, D] (fp32 softmax)."""
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Attention over [batch, seq, heads, head_dim].

    On TPU this is the Pallas kernel; elsewhere it falls back to the dense
    implementation (identical math) unless ``interpret=True`` forces the
    kernel through the Pallas interpreter (tests).
    """
    b, s, h, d = q.shape
    if interpret is None:
        if jax.default_backend() not in ("tpu", "axon"):
            return dense_attention(q, k, v, causal, scale)
        interpret = False
    sm_scale = d ** -0.5 if scale is None else scale
    # K and V live whole in VMEM (bandwidth-optimal: fetched once, not once
    # per query block).  That caps the per-device sequence length; beyond it,
    # shard the sequence instead (parallel.ring_attention over an sp axis).
    kv_bytes = 2 * s * d * jnp.dtype(k.dtype).itemsize
    if kv_bytes > 64 * 1024 * 1024:
        raise ValueError(
            f"flash_attention: K+V for seq_len={s}, head_dim={d} need "
            f"{kv_bytes / 2**20:.0f} MiB of VMEM (>64 MiB budget). Shard "
            "the sequence across devices with "
            "horovod_tpu.parallel.ring_attention instead.")
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if causal and block_q != block_k:
        block_q = block_k = min(block_q, block_k)
    # Pad the sequence up to a multiple of BOTH block sizes (the q grid and
    # the kv loop must each tile s_pad exactly), masking tail keys
    # in-kernel; a dense fallback here would materialize the [S, S] scores
    # this kernel exists to avoid.
    import math

    block = math.lcm(block_q, block_k)
    s_pad = -(-s // block) * block
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)

    out = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), sm_scale, causal,
                      block_q, block_k, bool(interpret), s)
    out = out.reshape(b, h, s_pad, d).transpose(0, 2, 1, 3)
    return out[:, :s]
