"""Eager device data plane: cached jitted fused collectives.

TPU-native analog of the reference's NCCL ops layer for the EAGER path
(reference: horovod/common/ops/nccl_operations.cc — NCCLAllreduce/
NCCLBroadcast execute ON the accelerator and the fused buffer stays
device-resident; SURVEY.md §2.2 and §7's design stance "the ops layer
compiles and caches jitted fused collectives").  Where the traced path
(``horovod_tpu.ops.collectives``) serves code already inside jit/shard_map,
this module serves *eager* enqueues of device-resident ``jax.Array``s: the
executor dispatches a cached, jitted fused collective over a
one-device-per-rank mesh instead of copying to host and riding the TCP
plane.

Correctness across ranks is negotiated, exactly like the reference decides
NCCL vs CPU ops from the request's device id: every enqueue announces a
``device`` capability bit, the coordinator ANDs the bits, and the response's
``device`` flag tells every rank which plane to dispatch — so a host numpy
on one rank demotes the collective to the host plane for all, and a
response flagged ``device`` is dispatched as the same XLA program in the
same negotiated order on every host (ICI moves the bytes).

Program caching (SURVEY.md §7 "Hard parts" #1): the collective program is
keyed by (mesh, reduce op, dtype, padded bucket length); fused buckets are
padded up to a small set of size classes ({1, 1.25, 1.5, 1.75}·2^k
elements) so steady-state cycles reuse compiled programs even when the
fusion composition varies cycle to cycle.  Pack/unpack are ordinary jits
cached by jax on member shapes.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..exceptions import HorovodInternalError
from ..utils.logging import get_logger
from ..wire import DataType, OpType, ReduceOp, validate_alltoall_splits

log = get_logger()

AXIS = "hvdev"

_MIN_BUCKET = 1024


def _shard_map():
    try:
        from jax import shard_map
    except ImportError:  # pre-0.4.x layout
        from jax.experimental.shard_map import shard_map
    return shard_map


_SUPPORTED_REDUCE = (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.MIN,
                     ReduceOp.MAX, ReduceOp.PRODUCT)


def bucket_len(n: int) -> int:
    """Pad a flat element count up to the {1, 1.25, 1.5, 1.75}·2^k size-class
    set (<= 25% padding, ~4 compiled programs per octave)."""
    if n <= _MIN_BUCKET:
        return _MIN_BUCKET
    base = 1 << (int(n).bit_length() - 1)  # largest pow2 <= n
    for num in (4, 5, 6, 7, 8):
        cls = base * num // 4
        if n <= cls:
            return cls
    return base * 2


class DevicePlane:
    """Executes negotiated ``device=True`` responses as jitted XLA
    collectives over a one-device-per-rank mesh."""

    def __init__(self, core, cfg):
        self._core = core
        self._cfg = cfg
        mode = os.environ.get("HOROVOD_DEVICE_PLANE", "auto").strip().lower()
        self._enabled = mode not in ("off", "0", "false", "no")
        self._lock = threading.Lock()
        # psid -> (mesh, ranks, my_device) or None (not buildable)
        self._meshes: Dict[int, Optional[tuple]] = {}
        self._programs: Dict[tuple, Any] = {}
        self._pack_fn = None
        self._unpack_fn = None
        self._scale_fn = None
        self.stats = {
            "allreduce": 0,       # fused device allreduce dispatches
            "broadcast": 0,       # device broadcast dispatches
            "reducescatter": 0,   # device reducescatter dispatches
            "allgather": 0,       # device allgather dispatches
            "alltoall": 0,        # device alltoall dispatches
            "identity": 0,        # single-member identity completions
            "quantized": 0,       # fused allreduces that rode the int8 ring
            "programs_built": 0,  # collective compile-cache misses
            "host_fallback": 0,   # device-resident entries demoted to host
            "late_device_put": 0,  # stale cache-replayed device=1 on a host entry
        }

    def _cached_program(self, key, build):
        """Double-checked program-cache access shared by every collective
        builder; ``build()`` runs outside the lock (jit/shard_map
        construction is slow) and ties break toward the first insert."""
        with self._lock:
            fn = self._programs.get(key)
        if fn is not None:
            return fn
        fn = build()
        with self._lock:
            if key not in self._programs:
                self._programs[key] = fn
                self.stats["programs_built"] += 1
            return self._programs[key]

    # -- enqueue-side capability -------------------------------------------
    def adopt(self, array, op: OpType, reduce_op: ReduceOp,
              psid: int):
        """The device-resident jax.Array behind ``array`` if this enqueue
        can ride the device plane, else None (host path).  This decides the
        rank's announced ``device`` capability bit, so it must only return
        an array when execute() is guaranteed to succeed locally."""
        if not self._enabled:
            return None
        if op == OpType.ALLREDUCE:
            if reduce_op not in _SUPPORTED_REDUCE:
                return None
        elif op == OpType.ALLGATHER:
            # Gathered first dims may differ per rank; the device program
            # pads to the max (counts are exchanged as metadata at execute
            # time — bytes stay on device).  Scalars ride the host plane.
            if getattr(array, "ndim", 0) == 0:
                return None
        elif op == OpType.ALLTOALL:
            if getattr(array, "ndim", 0) == 0:
                return None
        elif op == OpType.REDUCESCATTER:
            # Device reducescatter serves Sum/Average on evenly divisible
            # first dims (psum_scatter needs uniform chunks); the host
            # plane's extra-row slicing covers the remainder case.  Shape
            # equality across ranks is already negotiation-validated, so
            # the divisibility check agrees on every rank.
            if reduce_op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
                return None
            k = len(self._members(psid))
            d0 = array.shape[0] if getattr(array, "ndim", 0) else 0
            if k == 0 or d0 == 0 or d0 % k != 0:
                return None
        elif op != OpType.BROADCAST:
            return None
        try:
            import jax
        except ImportError:  # pragma: no cover
            return None
        if not isinstance(array, jax.Array) or isinstance(array, jax.core.Tracer):
            return None
        if not array.is_fully_addressable:
            # A multi-process global array is the SAME logical tensor on
            # every rank — not the per-rank contribution eager collectives
            # are defined over.
            return None
        if array.dtype == bool:
            return None  # the host plane's logical and/or semantics apply
        if not self.ready(psid):
            return None
        return array

    def note_host_fallback(self, name: str) -> None:
        """A device-resident tensor was demoted to the host plane by
        negotiation (a host tensor, unsupported op, or joined rank
        somewhere).  On TPU that means a chip->PCIe->TCP round-trip per
        collective — warn once so the perf trap is visible."""
        with self._lock:
            self.stats["host_fallback"] += 1
            warned = getattr(self, "_fallback_warned", False)
            self._fallback_warned = True
        if not warned:
            try:
                import jax

                on_tpu = jax.default_backend() == "tpu"
            except Exception:  # pragma: no cover
                on_tpu = False
            if on_tpu:
                log.warning(
                    "eager collective %r has a device-resident input but was "
                    "negotiated onto the HOST data plane (another rank "
                    "submitted a host tensor, an unsupported op/dtype, or a "
                    "rank is joined) — gradients will cross PCIe + host TCP. "
                    "Prefer jit/shard_map training steps, or keep every "
                    "rank's inputs device-resident. (warned once)", name)

    def ready(self, psid: int) -> bool:
        if self._core.size() == 1:
            return True
        return self._mesh_for(psid) is not None

    def invalidate(self, psid: int) -> None:
        with self._lock:
            self._meshes.pop(psid, None)
            for key in [k for k in self._programs if k[0] == psid]:
                self._programs.pop(key, None)

    # -- mesh / program construction ---------------------------------------
    def _mesh_for(self, psid: int):
        """(mesh, ranks, my_device) for the process set, or None when the
        jax runtime does not span its ranks (single-process jax with np>1,
        or a rank whose process owns no device)."""
        with self._lock:
            if psid in self._meshes:
                return self._meshes[psid]
        import jax
        from jax.sharding import Mesh

        result = None
        try:
            ranks = self._core.process_set_ranks(psid)
            by_proc: Dict[int, Any] = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            devs = [by_proc[r] for r in ranks]
            my = by_proc.get(self._core.rank())
            # hvd rank <-> jax process mapping comes from
            # jax.distributed.initialize(process_id=cfg.rank) (basics.init);
            # if the runtime was wired differently, "my" device may not be
            # addressable — then the plane cannot place local shards.
            if my is not None and my in jax.local_devices():
                mesh = Mesh(np.asarray(devs), (AXIS,))
                result = (mesh, list(ranks), my)
        except Exception as exc:  # noqa: BLE001 - capability probe
            log.debug("device plane unavailable for set %d: %s", psid, exc)
            result = None
        if result is not None:
            # Cache successes only: a transient probe failure (e.g. the
            # jax distributed runtime still connecting at first enqueue)
            # must not demote the set to the host plane for the whole job.
            with self._lock:
                self._meshes[psid] = result
        return result

    def _device_codec(self, rop: ReduceOp, dtype, length: int,
                      k: int) -> str:
        """The configured block-scaled codec (``int8``/``int4``/``int8g``)
        when this fused bucket should ride the quantized ring, else
        ``"none"``.  Demotion rules mirror the traced path (fp32 Sum/
        Average, payload >= HOROVOD_WIRE_COMPRESSION_MIN_BYTES, k > 1); the
        codec comes from config, which negotiation keeps rank-uniform, so
        every member picks the same program."""
        from . import quantize as _qz

        codec = getattr(self._cfg, "wire_compression_device", "none")
        if codec not in _qz.DEVICE_WIRE_CODECS or codec == "none":
            return "none"
        if k <= 1 or rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            return "none"
        if np.dtype(dtype) != np.float32:
            return "none"
        min_bytes = int(getattr(self._cfg, "wire_compression_min_bytes",
                                1 << 16))
        if length * 4 < min_bytes:
            return "none"
        return codec

    def _device_schedule(self, k: int) -> str:
        """Resolved ring schedule (``ring``/``bidi``/``torus``) for a
        ``k``-member plane — config's ``device_schedule`` (``auto`` picks
        from the member count) with infeasible choices demoted, so the
        value is a pure function of rank-uniform state."""
        from .collectives import resolve_device_schedule

        sched = getattr(self._cfg, "device_schedule", "auto")
        return resolve_device_schedule(k, sched)

    def _collective(self, psid: int, mesh, rop: ReduceOp, dtype, length: int,
                    codec: str = "none", schedule: str = "ring"):
        """Cached jitted fused-allreduce program over (k, L) global arrays:
        every member's [1, L] shard in, every member's reduced [1, L] shard
        out (out_specs stay device-varying so one program shape serves all
        reduce ops).  A block-scaled ``codec`` swaps the psum for the
        quantized ring under the resolved ``schedule`` (ops.quantize
        semantics; callers pre-filter via _device_codec /
        _device_schedule)."""
        key = (psid, "ar", int(rop), str(np.dtype(dtype)), length, codec,
               schedule, tuple(d.id for d in mesh.devices.flat))

        def build():
            import jax
            from jax import lax
            from jax.sharding import PartitionSpec as P
            shard_map = _shard_map()

            from .collectives import ensure_varying

            k = int(mesh.devices.size)

            def inner(x):  # [1, L]: this member's shard
                if codec != "none":
                    from .collectives import _quantized_ring_allreduce_sum

                    out = _quantized_ring_allreduce_sum(
                        x[0], AXIS, None, codec, schedule)[None]
                    if rop == ReduceOp.AVERAGE:
                        out = out / k
                elif rop == ReduceOp.SUM:
                    out = lax.psum(x, AXIS)
                elif rop == ReduceOp.AVERAGE:
                    out = lax.psum(x, AXIS) / k
                elif rop == ReduceOp.MIN:
                    out = lax.pmin(x, AXIS)
                elif rop == ReduceOp.MAX:
                    out = lax.pmax(x, AXIS)
                elif rop == ReduceOp.PRODUCT:
                    g = lax.all_gather(x, AXIS, axis=0, tiled=True)
                    out = jax.numpy.prod(g, axis=0, keepdims=True)
                else:  # pragma: no cover - adopt() filters
                    raise HorovodInternalError(
                        f"unsupported device reduce {rop}")
                return ensure_varying(out, AXIS)

            return jax.jit(shard_map(inner, mesh=mesh,
                                     in_specs=P(AXIS, None),
                                     out_specs=P(AXIS, None)))

        return self._cached_program(key, build)

    def _reducescatter_program(self, psid: int, mesh, rop: ReduceOp, dtype,
                               count: int, pre: float, post: float):
        """Cached jitted reducescatter over (k, N) global arrays: every
        member's full flat [1, N] in, its reduced [1, N/k] chunk out —
        lowered to psum_scatter ((k-1)/k of the bytes on the wire)."""
        key = (psid, "rs", int(rop), str(np.dtype(dtype)), count, pre, post,
               tuple(d.id for d in mesh.devices.flat))

        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P
            shard_map = _shard_map()

            from .collectives import ensure_varying

            k = int(mesh.devices.size)

            def inner(x):  # [1, N]: this member's full contribution
                flat = x[0]
                if pre != 1.0:
                    flat = flat * jnp.asarray(pre, flat.dtype)
                out = lax.psum_scatter(flat, AXIS, scatter_dimension=0,
                                      tiled=True)
                if rop == ReduceOp.AVERAGE:
                    out = out / k
                if post != 1.0:
                    out = out * jnp.asarray(post, out.dtype)
                return ensure_varying(out, AXIS)[None]

            return jax.jit(shard_map(inner, mesh=mesh,
                                     in_specs=P(AXIS, None),
                                     out_specs=P(AXIS, None)))

        return self._cached_program(key, build)

    def _broadcast_program(self, psid: int, mesh, dtype, shape, root_pos: int):
        key = (psid, "bc", str(np.dtype(dtype)), tuple(shape), root_pos,
               tuple(d.id for d in mesh.devices.flat))

        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P
            shard_map = _shard_map()

            from .collectives import ensure_varying

            def inner(x):  # [1, ...]: this member's value
                idx = lax.axis_index(AXIS)
                contrib = jnp.where(idx == root_pos, x, jnp.zeros_like(x))
                return ensure_varying(lax.psum(contrib, AXIS), AXIS)

            spec = P(AXIS, *([None] * len(shape)))
            return jax.jit(shard_map(inner, mesh=mesh, in_specs=spec,
                                     out_specs=spec))

        return self._cached_program(key, build)

    def _allgather_program(self, psid: int, mesh, dtype, counts: tuple,
                           rest: tuple):
        """Cached jitted allgather over (k, maxn, R) global arrays: every
        member's first-dim-padded [1, maxn, R] shard in, the full
        concatenation [1, total, R] out on every member.  ``counts`` (the
        per-member true first dims) is static — ragged gathers compile per
        counts signature, steady-state shapes hit the cache."""
        key = (psid, "ag", str(np.dtype(dtype)), counts, rest,
               tuple(d.id for d in mesh.devices.flat))

        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P
            shard_map = _shard_map()

            from .collectives import ensure_varying

            k = int(mesh.devices.size)

            def inner(x):  # [1, maxn, R]: this member's padded rows
                g = lax.all_gather(x[0], AXIS, axis=0)     # [k, maxn, R]
                parts = [g[i, :counts[i]] for i in range(k) if counts[i]]
                out = (jnp.concatenate(parts, axis=0) if parts
                       else g[:, :0].reshape((0,) + g.shape[2:]))
                return ensure_varying(out, AXIS)[None]     # [1, total, R]

            return jax.jit(shard_map(inner, mesh=mesh,
                                     in_specs=P(AXIS, None, None),
                                     out_specs=P(AXIS, None, None)))

        return self._cached_program(key, build)

    def _alltoall_program(self, psid: int, mesh, dtype, splits_mat: tuple,
                          restprod: int):
        """Cached jitted alltoall over (k, d0max, R) global arrays.
        ``splits_mat`` (row r = member r's per-destination send counts) is
        static.  Uniform splits lower to one tiled lax.all_to_all; ragged
        splits pad each (src, dst) chunk to the max count, exchange
        uniformly, then re-pack — extra wire bytes, but the payload stays
        on device (the host plane's ragged exchange is the alternative).
        Output is [1, recvmax, R] per member, sliced to the true receive
        count by the caller."""
        key = (psid, "a2a", str(np.dtype(dtype)), splits_mat, restprod,
               tuple(d.id for d in mesh.devices.flat))

        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P
            shard_map = _shard_map()

            from .collectives import ensure_varying

            k = int(mesh.devices.size)
            rows = [list(r) for r in splits_mat]
            recv_counts = [[rows[src][dst] for src in range(k)]
                           for dst in range(k)]
            recv_tot = [sum(rc) for rc in recv_counts]
            recvmax = max(max(recv_tot), 1)
            uniform = len({c for r in rows for c in r}) == 1

            if uniform:
                c = rows[0][0]

                def inner(x):  # [1, d0max, R]; d0max == k*c here
                    swapped = lax.all_to_all(      # row i <- member i's chunk
                        x[0].reshape(k, c, -1), AXIS, split_axis=0,
                        concat_axis=0, tiled=False)
                    out = swapped.reshape(k * c, -1)
                    return ensure_varying(out, AXIS)[None]

                return jax.jit(shard_map(inner, mesh=mesh,
                                         in_specs=P(AXIS, None, None),
                                         out_specs=P(AXIS, None, None)))

            cmax = max(max(c for r in rows for c in r), 1)

            def pack_for(r):
                offs = np.concatenate([[0], np.cumsum(rows[r])])

                def pack(x):  # [d0max, R] -> [k, cmax, R] padded chunks
                    chunks = []
                    for j in range(k):
                        seg = x[int(offs[j]):int(offs[j + 1])]
                        pad = cmax - seg.shape[0]
                        if pad:
                            z = ensure_varying(
                                jnp.zeros((pad,) + seg.shape[1:], seg.dtype),
                                AXIS)
                            seg = jnp.concatenate([seg, z])
                        chunks.append(seg)
                    return jnp.stack(chunks)

                return pack

            def unpack_for(me):
                def unpack(g):  # [k, cmax, R] rows from each src, padded
                    parts = [g[src, :recv_counts[me][src]]
                             for src in range(k) if recv_counts[me][src]]
                    out = (jnp.concatenate(parts, axis=0) if parts
                           else g[:, :0].reshape((0,) + g.shape[2:]))
                    pad = recvmax - out.shape[0]
                    if pad:
                        z = ensure_varying(
                            jnp.zeros((pad,) + out.shape[1:], out.dtype),
                            AXIS)
                        out = jnp.concatenate([out, z])
                    return out

                return unpack

            def inner(x):  # [1, d0max, R]
                me = lax.axis_index(AXIS)
                packed = lax.switch(
                    me, [lambda _, r=r: pack_for(r)(x[0]) for r in range(k)],
                    None)
                swapped = lax.all_to_all(packed, AXIS, split_axis=0,
                                         concat_axis=0, tiled=False)
                out = lax.switch(
                    me, [lambda g, r=r: unpack_for(r)(g) for r in range(k)],
                    swapped)
                return ensure_varying(out, AXIS)[None]    # [1, recvmax, R]

            return jax.jit(shard_map(inner, mesh=mesh,
                                     in_specs=P(AXIS, None, None),
                                     out_specs=P(AXIS, None, None)))

        return self._cached_program(key, build)

    def _pack(self):
        """Jitted fuse: concat member tensors flat, optional prescale, pad
        to the bucket length (MemcpyInFusionBuffer analog, on device).
        Scale factors are static (compile-time constants): an eager
        ``jnp.asarray(pre)`` would be a host->device scalar transfer, which
        the no-host-copy guarantee (and its transfer-guard test) forbids."""
        if self._pack_fn is None:
            import jax
            import jax.numpy as jnp

            def pack(arrays, pre, length):
                flat = (jnp.concatenate([a.ravel() for a in arrays])
                        if len(arrays) > 1 else arrays[0].ravel())
                if pre != 1.0:
                    flat = flat * jnp.asarray(pre, flat.dtype)
                pad = length - flat.size
                if pad:
                    flat = jnp.concatenate(
                        [flat, jnp.zeros((pad,), flat.dtype)])
                return flat.reshape(1, length)

            self._pack_fn = jax.jit(pack, static_argnums=(1, 2))
        return self._pack_fn

    def _unpack(self):
        """Jitted unfuse: slice the reduced flat bucket back into member
        shapes, optional postscale (MemcpyOutFusionBuffer analog)."""
        if self._unpack_fn is None:
            import jax

            import jax.numpy as jnp

            def unpack(row, post, shapes):
                flat = row.reshape(-1)
                outs = []
                off = 0
                for shp in shapes:
                    n = int(np.prod(shp)) if shp else 1
                    seg = flat[off:off + n].reshape(shp)
                    if post != 1.0:
                        seg = seg * jnp.asarray(post, seg.dtype)
                    outs.append(seg)
                    off += n
                return outs

            self._unpack_fn = jax.jit(unpack, static_argnums=(1, 2))
        return self._unpack_fn

    def _scale(self):
        if self._scale_fn is None:
            import jax
            import jax.numpy as jnp

            def scale(x, a, b):
                if a != 1.0:
                    x = x * jnp.asarray(a, x.dtype)
                if b != 1.0:
                    x = x * jnp.asarray(b, x.dtype)
                return x

            self._scale_fn = jax.jit(scale, static_argnums=(1, 2))
        return self._scale_fn

    # -- execution ---------------------------------------------------------
    def execute(self, resp, entries: Sequence) -> None:
        """Run a negotiated ``device=True`` response; fills entry results
        with device-resident jax.Arrays (no host copies anywhere in the
        steady state).

        A response-cache replay carries the bit of the ORIGINAL
        negotiation, so a tensor that flipped device->host since then can
        arrive here without a device array — place its host bytes on
        device explicitly (one slow step, correct result; the response
        cache evicts/re-learns the signature only when metadata changes,
        not the plane)."""
        import jax

        for e in entries:
            if e.device_array is None:
                e.device_array = jax.device_put(np.ascontiguousarray(e.array))
                with self._lock:
                    self.stats["late_device_put"] += 1
        if resp.op == OpType.ALLREDUCE:
            self._exec_allreduce(resp, entries)
        elif resp.op == OpType.BROADCAST:
            self._exec_broadcast(resp, entries[0])
        elif resp.op == OpType.REDUCESCATTER:
            self._exec_reducescatter(resp, entries[0])
        elif resp.op == OpType.ALLGATHER:
            self._exec_allgather(resp, entries)
        elif resp.op == OpType.ALLTOALL:
            self._exec_alltoall(resp, entries[0])
        else:
            raise HorovodInternalError(
                f"op {resp.op} is not served by the device plane")

    def _members(self, psid: int) -> List[int]:
        return self._core.process_set_ranks(psid)

    def _exec_allreduce(self, resp, entries: Sequence) -> None:
        import jax
        import jax.numpy as jnp

        psid = resp.process_set_id
        rop = entries[0].reduce_op
        pre = entries[0].prescale_factor
        post = entries[0].postscale_factor
        if len(self._members(psid)) == 1:
            # Single-member set: every supported reduce op is the identity
            # (modulo scale factors) — complete without any data movement,
            # preserving each input's sharding.
            for e in entries:
                x = e.device_array
                if pre != 1.0 or post != 1.0:
                    x = self._scale()(x, float(pre), float(post))
                e.result = x
            with self._lock:
                self.stats["identity"] += len(entries)
            return

        mesh, ranks, my_dev = self._mesh_for(psid)
        arrays = [jax.device_put(e.device_array, my_dev) for e in entries]
        dtype = arrays[0].dtype
        total = int(sum(a.size for a in arrays))
        length = bucket_len(total)
        packed = jax.device_put(
            self._pack()(tuple(arrays), float(pre), length), my_dev)
        garr = self._to_global(mesh, [packed])
        codec = self._device_codec(rop, dtype, length, len(ranks))
        schedule = self._device_schedule(len(ranks))
        out = self._collective(psid, mesh, rop, dtype, length, codec,
                               schedule)(garr)
        row = self._shard_on(out, my_dev)
        shapes = tuple(tuple(e.device_array.shape) for e in entries)
        results = self._unpack()(row, float(post), shapes)
        for e, r in zip(entries, results):
            e.result = r
        if codec != "none":
            from . import quantize as _qz

            _qz.note_device_bytes(
                *_qz.ring_bytes(length, len(ranks), codec, schedule))
        with self._lock:
            self.stats["allreduce"] += 1
            if codec != "none":
                self.stats["quantized"] += 1

    def _exec_reducescatter(self, resp, entry) -> None:
        import jax

        psid = resp.process_set_id
        members = self._members(psid)
        pre = float(entry.prescale_factor)
        post = float(entry.postscale_factor)
        if len(members) == 1:
            # One member keeps the whole reduced buffer (host-plane
            # semantics at n=1): identity modulo scales.
            x = entry.device_array
            if pre != 1.0 or post != 1.0:
                x = self._scale()(x, pre, post)
            entry.result = x
            with self._lock:
                self.stats["identity"] += 1
            return
        mesh, ranks, my_dev = self._mesh_for(psid)
        k = len(ranks)
        x = jax.device_put(entry.device_array, my_dev)
        row = x.reshape(1, -1)
        garr = self._to_global(mesh, [row])
        fn = self._reducescatter_program(psid, mesh, entry.reduce_op,
                                         x.dtype, row.shape[1], pre, post)
        out = fn(garr)
        chunk_rows = x.shape[0] // k
        entry.result = self._shard_on(out, my_dev).reshape(
            (chunk_rows,) + tuple(x.shape[1:]))
        with self._lock:
            self.stats["reducescatter"] += 1

    def _exec_allgather(self, resp, entries: Sequence) -> None:
        """Device allgather: per-rank first dims are exchanged as int64
        METADATA over the host ctrl plane (same channel negotiation uses —
        a few bytes), then the payload rides one cached XLA all_gather.
        Ragged first dims pad to the max and slice inside the program."""
        import jax

        psid = resp.process_set_id
        members = self._members(psid)
        if len(members) == 1:
            for e in entries:
                e.result = e.device_array
            with self._lock:
                self.stats["identity"] += len(entries)
            return
        mesh, ranks, my_dev = self._mesh_for(psid)
        k = len(ranks)
        dims = np.ascontiguousarray(
            [int(e.device_array.shape[0]) for e in entries], dtype=np.int64)
        stacked, _ = self._core.allgather_buffer(dims, psid)
        per_rank = np.asarray(stacked, dtype=np.int64).reshape(k, len(entries))
        for j, e in enumerate(entries):
            counts = tuple(int(c) for c in per_rank[:, j])
            maxn = max(max(counts), 1)
            x = jax.device_put(e.device_array, my_dev)
            rest = tuple(x.shape[1:])
            # Explicit row width: a -1 reshape is ambiguous for zero-row
            # contributions (size 0), which the ragged program supports.
            restprod = int(np.prod(rest, dtype=np.int64)) if rest else 1
            row = x.reshape((1, x.shape[0], restprod))
            if x.shape[0] < maxn:
                row = self._pad_rows()(row, maxn)
            garr = self._to_global(mesh, [row])
            fn = self._allgather_program(psid, mesh, x.dtype, counts, rest)
            out = fn(garr)
            e.result = self._shard_on(out, my_dev).reshape(
                (int(sum(counts)),) + rest)
        with self._lock:
            self.stats["allgather"] += 1

    def _exec_alltoall(self, resp, entry) -> None:
        """Device alltoall: split vectors are exchanged as metadata (as in
        allgather), then a cached program performs the exchange — one tiled
        lax.all_to_all when splits are uniform, a pad-to-max exchange when
        ragged.  Mirrors the host plane's validation and recv_splits."""
        import jax

        psid = resp.process_set_id
        members = self._members(psid)
        k = len(members)
        x = entry.device_array
        splits = validate_alltoall_splits(entry.splits, x.shape[0], k)
        if k == 1:
            entry.result = x
            entry.recv_splits = splits.copy()
            with self._lock:
                self.stats["identity"] += 1
            return
        mesh, ranks, my_dev = self._mesh_for(psid)
        my_pos = ranks.index(self._core.rank())
        stacked, _ = self._core.allgather_buffer(splits, psid)
        mat = np.asarray(stacked, dtype=np.int64).reshape(k, k)
        if int(mat.sum()) == 0:  # nothing moves anywhere
            entry.result = x[:0]
            entry.recv_splits = np.zeros((k,), dtype=np.int64)
            with self._lock:
                self.stats["alltoall"] += 1
            return
        splits_mat = tuple(tuple(int(c) for c in row) for row in mat)
        rest = tuple(x.shape[1:])
        x = jax.device_put(x, my_dev)
        restprod = int(np.prod(rest, dtype=np.int64)) if rest else 1
        row = x.reshape((1, x.shape[0], restprod))
        d0max = max(int(mat.sum(axis=1).max()), 1)
        if row.shape[1] < d0max:
            row = self._pad_rows()(row, d0max)
        garr = self._to_global(mesh, [row])
        fn = self._alltoall_program(psid, mesh, x.dtype, splits_mat,
                                    int(row.shape[2]))
        out = fn(garr)
        recv = [int(mat[src, my_pos]) for src in range(k)]
        entry.result = self._shard_on(out, my_dev)[0, :sum(recv)].reshape(
            (sum(recv),) + rest)
        entry.recv_splits = np.asarray(recv, dtype=np.int64)
        with self._lock:
            self.stats["alltoall"] += 1

    def _pad_rows(self):
        """Jitted zero-pad of a [1, n, R] row to [1, target, R] (device-side
        — the no-host-copy guarantee holds through ragged paths too)."""
        if getattr(self, "_pad_fn", None) is None:
            import jax
            import jax.numpy as jnp

            def pad(row, target):
                n = row.shape[1]
                z = jnp.zeros((1, target - n, row.shape[2]), row.dtype)
                return jnp.concatenate([row, z], axis=1)

            self._pad_fn = jax.jit(pad, static_argnums=(1,))
        return self._pad_fn

    def _exec_broadcast(self, resp, entry) -> None:
        import jax

        psid = resp.process_set_id
        members = self._members(psid)
        if len(members) == 1:
            entry.result = entry.device_array
            with self._lock:
                self.stats["identity"] += 1
            return
        mesh, ranks, my_dev = self._mesh_for(psid)
        root_pos = ranks.index(entry.root_rank)
        x = jax.device_put(entry.device_array, my_dev)
        garr = self._to_global(mesh, [x[None]])
        fn = self._broadcast_program(psid, mesh, x.dtype, x.shape, root_pos)
        out = fn(garr)
        entry.result = self._shard_on(out, my_dev).reshape(x.shape)
        with self._lock:
            self.stats["broadcast"] += 1

    # -- global-array plumbing (shared with the simulation tests) ----------
    def _to_global(self, mesh, rows: List):
        """Assemble per-member [1, ...] rows into the (k, ...) global array.
        In production ``rows`` holds this process's single shard; the
        simulation tests (and the dryrun gate) pass one row per mesh device
        of a local mesh — the same code path either way, zero-copy."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        row0 = rows[0]
        k = int(mesh.devices.size)
        sharding = NamedSharding(mesh, P(AXIS, *([None] * (row0.ndim - 1))))
        gshape = (k,) + tuple(row0.shape[1:])
        if len(rows) > 1:
            # Simulation: commit row i to mesh device i.
            rows = [jax.device_put(r, d)
                    for r, d in zip(rows, list(mesh.devices.flat))]
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, rows)

    @staticmethod
    def _shard_on(garr, device):
        """The [1, ...] result shard residing on ``device``."""
        for s in garr.addressable_shards:
            if s.device == device:
                return s.data
        raise HorovodInternalError(
            "device plane result has no shard on the local mesh device")
