"""GSPMD compiler-inserted data plane.

The third data plane, next to the host TCP ring (runtime.py) and the
eager device plane (ops/device_plane.py).  Where the eager plane builds
*explicit* collective programs — shard_map bodies whose ``lax.psum`` /
``lax.ppermute`` sequence is fixed at trace time — this plane only
*annotates*: gradients are batch-computed under a named mesh, tagged with
``jax.lax.with_sharding_constraint``, and ``jax.jit``'s SPMD partitioner
(GSPMD) inserts and schedules the collectives itself.  XLA is then free
to overlap reduce traffic with the optimizer math, which is where the
MLPerf TPU-pod submissions win their step time (PAPERS.md).

Demotion contract (the PR 10/15 interaction): a plane request that cannot
compose falls back to the eager plane *deterministically* and
*bit-identically* — the annotations only guide XLA's scheduler, never the
math — and every demotion increments a named counter here so the choice
is observable (`plane_counters()`), mirroring the quantized plane's byte
counters (ops/quantize.py).

Demotion reasons:

- ``world1``    — the mesh has a single device; there is no collective to
                  overlap, and XLA would fold the annotations away anyway.
- ``quantized`` — ``device=<codec>`` compression is active.  The quantized
                  collectives are explicit ppermute rings built inside
                  shard_map; GSPMD cannot schedule through them, so the
                  optimizer keeps the eager plane end to end rather than
                  mixing planes within one step.
- ``dtype``     — a non-fp32 leaf (per leaf, at trace time).  The parity
                  bar this plane is pinned to (tests/single/
                  test_gspmd_plane.py) is fp32-reduction-order only, so
                  other dtypes skip the annotation and take whatever
                  layout XLA picks — same values, no constraint.
- ``no_jax``    — jax is not importable (pure-python host-ring build).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

BATCH_AXIS = "batch"
MODEL_AXIS = "model"

_LOCK = threading.Lock()
_COUNTERS: Dict[str, int] = {}


def _bump(reason: str) -> None:
    with _LOCK:
        _COUNTERS[reason] = _COUNTERS.get(reason, 0) + 1


def note_demotion(reason: str) -> None:
    """Record a demotion decided outside this module (the optimizer
    demotes for its own reasons too — accumulation, process sets, ZeRO-1
    sharding — and those must be just as observable)."""
    _bump(reason)


def plane_counters() -> Dict[str, int]:
    """Snapshot of demotion/selection counters: ``gspmd`` (optimizers that
    resolved to the gspmd plane), ``demote_world1`` / ``demote_quantized``
    / ``demote_no_jax`` (per optimizer), ``demote_dtype`` (per non-fp32
    leaf, at trace time)."""
    with _LOCK:
        return dict(_COUNTERS)


def reset_plane_counters() -> None:
    with _LOCK:
        _COUNTERS.clear()


# ---------------------------------------------------------------------------
# Mesh construction
# ---------------------------------------------------------------------------

def _model_factors(n: int) -> Tuple[int, int]:
    """(batch, model) factorization for a 2-D mesh over ``n`` devices,
    degrading the model axis as devices run out (SNIPPETS.md [3]): 8+
    devices keep 2-way batch and give the rest to model, 4+ go 2x2, 2 go
    1x2, and a single device collapses to 1x1."""
    if n >= 8:
        return 2, n // 2
    if n >= 4:
        return 2, 2
    if n >= 2:
        return 1, 2
    return 1, 1


def build_gspmd_mesh(devices=None, model_parallel: bool = False):
    """Named ``Mesh`` for the gspmd plane: 1-D ``batch`` over all visible
    devices by default, or 2-D ``batch`` x ``model`` when the caller wants
    tensor sharding on the same substrate (SNIPPETS.md [1]-[3])."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if not model_parallel:
        return Mesh(np.asarray(devices), (BATCH_AXIS,))
    b, m = _model_factors(len(devices))
    arr = np.asarray(devices[: b * m]).reshape((b, m))
    return Mesh(arr, (BATCH_AXIS, MODEL_AXIS))


# ---------------------------------------------------------------------------
# Sharding-tree utilities
# ---------------------------------------------------------------------------

def batch_pspec(leaf, mesh) -> Any:
    """PartitionSpec sharding ``leaf``'s leading dim over the batch axis
    when it divides evenly, replicated otherwise (the naive-but-safe rule
    of SNIPPETS.md [2] — a non-divisible dim must not silently pad)."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[BATCH_AXIS]
    shape = getattr(leaf, "shape", ())
    if len(shape) >= 1 and n > 1 and shape[0] % n == 0:
        return P(BATCH_AXIS, *([None] * (len(shape) - 1)))
    return P()


def tree_pspecs(tree, mesh):
    """Pytree of PartitionSpec leaves mirroring ``tree``: batch-sharded
    where the leading dim divides the batch axis, replicated otherwise."""
    import jax

    return jax.tree_util.tree_map(lambda l: batch_pspec(l, mesh), tree)


def tree_shardings(tree, mesh):
    """Pytree of ``NamedSharding`` leaves mirroring ``tree`` (same rule as
    :func:`tree_pspecs`) — the form ``jax.device_put`` / ``jax.jit``
    in_shardings accept without an ambient mesh context."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda l: NamedSharding(mesh, batch_pspec(l, mesh)), tree)


def replicated_sharding(mesh):
    """NamedSharding replicating a leaf over the whole mesh — the
    constraint the optimizer pins gradients/updates to."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# Trace-time annotation
# ---------------------------------------------------------------------------

def constrain_grad_leaf(leaf, mesh):
    """Pin one gradient leaf replicated over ``mesh`` so GSPMD schedules
    its (implicit, backprop-inserted) reduction where it can overlap with
    the optimizer math.  Non-fp32 leaves demote per leaf: the annotation
    is skipped (``demote_dtype``) and the leaf passes through bit-identically.
    """
    import jax
    import jax.numpy as jnp

    if getattr(leaf, "dtype", None) != jnp.float32:
        _bump("demote_dtype")
        return leaf
    return jax.lax.with_sharding_constraint(leaf, replicated_sharding(mesh))


def constrain_grads(grads, mesh):
    """Annotate every fp32 leaf of a gradient pytree with a replicated
    sharding constraint (see :func:`constrain_grad_leaf`)."""
    import jax

    return jax.tree_util.tree_map(
        lambda l: constrain_grad_leaf(l, mesh), grads)


# ---------------------------------------------------------------------------
# Plane resolution
# ---------------------------------------------------------------------------

def default_mesh():
    """Mesh the optimizer constrains against when the caller passes none:
    the 1-D batch mesh over all visible devices."""
    return build_gspmd_mesh()


def data_plane_default() -> str:
    """Configured plane request: the live context's ``Config.data_plane``
    when initialized (runtime.py consumed it at init), else
    HOROVOD_DATA_PLANE — same fallback shape as the device plane's codec
    and schedule defaults (ops/collectives.py)."""
    try:
        from ..context import HorovodContext
        if HorovodContext.initialized():
            return getattr(HorovodContext.instance().cfg,
                           "data_plane", "auto")
    except Exception:
        pass
    from ..utils.env import get_data_plane
    return get_data_plane()


def resolve_plane(request: Optional[str] = None, mesh=None,
                  device_codec: Optional[str] = None,
                  count: bool = True) -> Tuple[str, Any]:
    """Resolve a plane request to ``("eager", None)`` or
    ``("gspmd", mesh)``.

    ``request`` is ``auto`` / ``eager`` / ``gspmd`` (None reads
    HOROVOD_DATA_PLANE via utils.env); demotions are deterministic in the
    mesh size and codec config — every rank resolves identically — and
    each bumps its counter (module docstring).  An explicit ``eager``
    request is a choice, not a demotion: no counter.  ``count=False``
    resolves silently — the ``auto`` request probes capability on every
    optimizer construction and must not read as a stream of demotions.
    """
    if request is None:
        request = data_plane_default()
    request = (request or "auto").strip().lower()
    bump = _bump if count else (lambda reason: None)
    if request == "eager":
        return "eager", None
    try:
        import jax  # noqa: F401
    except Exception:
        bump("demote_no_jax")
        return "eager", None
    if device_codec is not None and device_codec != "none":
        bump("demote_quantized")
        return "eager", None
    if mesh is None:
        mesh = default_mesh()
    if mesh.size < 2:
        bump("demote_world1")
        return "eager", None
    bump("gspmd")
    return "gspmd", mesh
