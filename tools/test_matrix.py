#!/usr/bin/env python
"""Controller/config test matrix (reference analog: the docker-compose +
Buildkite matrix exercising framework x controller x device combos,
SURVEY.md §4.5).

Runs a canonical collective-correctness workload across every supported
combination of:

- core:    native (LocalController at np=1, socket controller at np>1)
           x pure-python (np=1 only — the fallback core's contract)
- np:      1, 2, 3
- fusion:  default threshold / disabled (HOROVOD_FUSION_THRESHOLD=0)
- cache:   default capacity / disabled (HOROVOD_CACHE_CAPACITY=0)
- plane:   shared-memory / pipelined TCP ring (HOROVOD_SHM_DISABLE=1) /
           legacy whole-segment TCP ring (+HOROVOD_RING_CHUNK_BYTES=0),
           np>1 only / hierarchical (HOROVOD_HIERARCHICAL_ALLREDUCE=1 over
           two fake hosts via HOROVOD_HIER_FAKE_HOSTS=2), np>=3 only —
           smaller np degenerates to one rank per fake host
- wire:    none / bf16 / int8 (HOROVOD_WIRE_COMPRESSION) — codecs engage
           on the hier plane's cross-host leader ring; plus demotion
           combos where the knob is set on an all-local topology and the
           coordinator must turn it into a no-op
- metrics: off / on (HOROVOD_METRICS=1) — native-core combos appended to
           the full set; the workload asserts the registry populated
           (cycle occupancy, negotiation-wait histogram) when enabled
- ctrl_tree: auto (default) / on (HOROVOD_CONTROL_TREE, the leader
           tree) / d3 (tree forced three levels deep via
           HOROVOD_CONTROL_TREE_DEPTH=3 over three fake hosts, the v12
           adaptive-depth plane: coordinator <- super-leader <- leader) —
           "on"/"d3" combos run over fake hosts since auto stays flat
           below np=8; one on-combo and one d3-combo in the quick set,
           the rest (plus a single-host demotion row) full only
- flight:  def (ambient default) / on / off (HOROVOD_FLIGHT_RECORDER) —
           "on" combos assert the black box recorded the workload
           (hvd.flight_record() non-empty, right rank), "off" combos that
           it reports {}; one on-combo in the quick set
- autopilot: off / on (HOROVOD_AUTOPILOT=1) — "on" combos route through
           the elastic driver with the fleet-autopilot policy thread
           polling the coordinator; a healthy fleet must produce zero
           decisions and an unchanged workload result; one on-combo in
           the quick set
- qdev:    off / <codec>[:<schedule>] / demote (the
           HOROVOD_WIRE_COMPRESSION ``device=`` plane) — the in-jit
           block-scaled device ring, exercised over a forced 4-device CPU
           host platform; codec is int8 / int4 / int8g, the optional
           schedule suffix pins HOROVOD_DEVICE_SCHEDULE (ring/bidi/torus).
           A codec value asserts the auto-dispatch engaged (byte counters
           moved, scale/2-bounded error — int4's bound is 127/7 wider),
           "demote" that the min-bytes floor keeps the codec cold and the
           result bit-identical to the plain collective; np=1 rows plus
           one cross-plane row (host bf16 x device int8); int8 and
           int4:bidi combos in the quick set
- migrate: off / on (HOROVOD_MIGRATE_REPLICAS) — "on" combos commit an
           elastic ObjectState and assert peer-shard replication landed
           the committed snapshot bit-exact on the ring successors' shard
           stores (docs/elastic.md "Zero-downtime migration"); one
           on-combo in the quick set
- trace:   def (ambient default: tracing on) / on / off
           (HOROVOD_STEP_TRACE) — "on" combos assert the causal step ring
           recorded the workload (completed steps with wall-clock bounds
           and a non-zero 5-phase breakdown; fleet attribution on the
           coordinator at np>1), "off" combos that hvd.step_trace()
           reports {}; one on-combo in the quick set
- fleet:   def (ambient default) / on / off (HOROVOD_FLEET_TELEMETRY,
           the v11 sketch sections; rides the metrics plane, so "on"
           combos force HOROVOD_METRICS=1) — "on" combos assert the
           coordinator's true fleet histograms populated
           (metrics()["fleet"]) and hvd.fleet_history() serves the
           fleethistory-v1 payload, "off" combos that both stay empty;
           one on-combo in the quick set
- dplane:  off / gspmd / diff (HOROVOD_DATA_PLANE, the gspmd
           compiler-inserted gradient-exchange plane over a forced
           4-device host) — "gspmd" asserts the env-plumbed request
           reaches the optimizer (ops/gspmd_plane.py selection counter)
           and a jitted train step runs; "diff" trains the same problem
           under the eager and gspmd calling conventions and asserts
           parity within fp32 reduction-order tolerance; the gspmd
           on-combo rides in the quick set
- hloinspect: def / on / off (HOROVOD_HLO_INSPECT, compiled-collective
           introspection over a forced 8-device host) — "on" runs a
           gspmd-plane train step through ops/hlo_inspect.instrument and
           asserts a non-empty collective inventory whose analytic byte
           totals match the live gspmd counters exactly; "off" asserts
           HOROVOD_HLO_INSPECT=0 returns the step unchanged (identity
           wrapper, zero per-step work) and every counter stays zero;
           the on-combo rides in the quick set

Plus non-workload check rows: `lint` (tools/hvd_lint.py — ABI/env/protocol
consistency, both sets), `lint-atomic`/`lint-lockorder`/`lint-sigsafe`
(the concurrency-discipline passes standalone via `--only`, both sets),
`fault-spec` (the HOROVOD_FAULT_INJECT parser
contract, both sets), and — full set only — the ASan/UBSan selftest
builds, the `chaos` fault-injection/fast-abort selftest, the np=4
fault-injection pytest (`fault-np4`: abort bound, corrupt-tag fail-fast,
elastic recovery under --fault-inject), the np=4 chaos-postmortem pytest
(`postmortem-np4`: injected death -> merged postmortem.json with the right
culprit within the abort bound), the np=4 hands-off autopilot chaos loop
(`autopilot-np4`: persistent injected straggle -> detect, evict, elastic
recovery, blacklist-expiry re-admission — zero human input), the np=4
zero-downtime migration chaos pytest (`migration-np4`: rank death ->
re-form np=3 resuming bit-identically from peer shards with zero
checkpoint reads -> blacklist-expiry re-grow to np=4, plus the degraded
checkpoint-fallback path), the np=4 live-cockpit attribution pytest
(`cockpit-np4`: injected coordinator-recv delay -> the live /state
snapshot AND tools/critical_path.py both name the delayed rank /
negotiation-wait), the np=4 anomaly-sentinel chaos pytest
(`sentinel-np4`: persistent injected delay on one rank -> sentinel
anomaly naming that rank, journaled and flight-recorded strictly before
the eviction rule can fire), the np=256 control-plane soak (`ctrl-soak`:
flat vs tree coordinator message counts, plus a migration-noting row),
the np=1024 / 64-fake-host pod-scale soak (`ctrl-soak-1024`: the
auto-grown three-level v12 tree holds coordinator inbound at O(fanout),
bucket-exact sketch merges, chaos arms at every tree level), the np=8
tree-vs-flat parity pytest (`ctrl-np8`), and the np=8 adaptive-depth
pytest (`ctrl-depth-np8`: flat == depth-2 == depth-3 parity plus the
super-leader-death abort bound).

Usage:
    python tools/test_matrix.py              # full matrix
    python tools/test_matrix.py --quick      # one combo per axis value

Prints one PASS/FAIL line per combination and exits nonzero if any fail.
"""

from __future__ import annotations

import argparse
import itertools
import os
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP_DIR = os.path.join(REPO, "horovod_tpu", "cpp")

WORKLOAD = textwrap.dedent("""
    import os
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    import horovod_tpu as hvd

    hvd.init(build_mesh=False)
    r, s = hvd.rank(), hvd.size()

    # allreduce ops + dtypes
    x = np.full(33, float(r + 1), np.float32)
    total = s * (s + 1) / 2.0
    np.testing.assert_allclose(hvd.allreduce(x, op=hvd.Sum, name="m.sum"),
                               total)
    np.testing.assert_allclose(hvd.allreduce(x, op=hvd.Average, name="m.avg"),
                               total / s)
    np.testing.assert_allclose(hvd.allreduce(x, op=hvd.Min, name="m.min"), 1.0)
    np.testing.assert_allclose(hvd.allreduce(x, op=hvd.Max, name="m.max"),
                               float(s))
    v = (np.arange(6) + r).astype(np.int64)
    expected = sum((np.arange(6) + rr) for rr in range(s))
    np.testing.assert_array_equal(hvd.allreduce(v, op=hvd.Sum, name="m.i64"),
                                  expected)

    # fusion sweep: many small tensors in one window
    handles = [hvd.allreduce_async(np.full(8, float(i + r), np.float32),
                                   op=hvd.Sum, name=f"m.f.{i}")
               for i in range(40)]
    for i, h in enumerate(handles):
        np.testing.assert_allclose(hvd.synchronize(h),
                                   s * i + s * (s - 1) / 2.0)

    # cache steady state: identical negotiation repeated
    for it in range(20):
        out = hvd.allreduce(np.full(16, float(r), np.float32), op=hvd.Sum,
                            name="m.cached")
        np.testing.assert_allclose(out, s * (s - 1) / 2.0)

    # ragged allgather
    g = np.asarray(hvd.allgather(np.full((r + 1, 2), float(r), np.float32),
                                 name="m.ag"))
    assert g.shape == (s * (s + 1) // 2, 2), g.shape

    # broadcast from every root
    for root in range(s):
        out = hvd.broadcast(np.full(5, float(r), np.float64), root_rank=root,
                            name=f"m.bc.{root}")
        np.testing.assert_allclose(out, float(root))

    # equal-splits alltoall
    data = (np.arange(2 * s, dtype=np.float32) + 10 * r).reshape(2 * s, 1)
    out, _ = hvd.alltoall(data, splits=[2] * s, name="m.a2a")
    assert np.asarray(out).shape == (2 * s, 1)

    # process set (channel + lane + per-set plane)
    if s >= 2:
        ps = hvd.add_process_set(list(range(s - 1)))
        if r < s - 1:
            out = hvd.allreduce(np.full(7, float(r + 1), np.float32),
                                op=hvd.Sum, process_set=ps, name="m.ps")
            np.testing.assert_allclose(out, (s - 1) * s / 2.0)

    # big fp32 payload above the wire-compression floor: rides the codec
    # on cross-host topologies (tolerance keyed off the knob; the small
    # tensors above stay under the floor, so their exact asserts hold).
    wire = os.environ.get("HOROVOD_WIRE_COMPRESSION", "none")
    if "=" in wire:  # per-plane syntax: the host ring takes the host= entry
        wire = dict(kv.split("=", 1)
                    for kv in wire.split(",")).get("host", "none")
    wtol = {"bf16": dict(rtol=0.04, atol=1e-3),
            "int8": dict(rtol=0.05, atol=6.0)}.get(wire, dict(rtol=1e-6))
    big = ((np.arange(1 << 16) % 251) + r).astype(np.float32)
    wexp = sum(((np.arange(1 << 16) % 251) + rr).astype(np.float32)
               for rr in range(s))
    np.testing.assert_allclose(hvd.allreduce(big, op=hvd.Sum, name="m.wire"),
                               wexp, **wtol)

    # qdev axis: the in-jit device-plane ring (HOROVOD_WIRE_COMPRESSION
    # device=<codec>) over the forced multi-device host platform.  A codec
    # value ("int8" / "int4" / "int8g", optional ":<schedule>" suffix) must
    # engage the auto-dispatch (byte counters move) within the codec's
    # scale/2 error bound; "demote" pins the min-bytes floor: codec stays
    # cold and the result is bit-identical to the plain collective.
    qdev = os.environ.get("HVD_MATRIX_QDEV", "off")
    if qdev != "off":
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        import horovod_tpu.ops.quantize as qz
        devs = jax.devices()
        assert len(devs) >= 2, "qdev combo expects a forced multi-dev host"
        mesh = Mesh(np.asarray(devs), ("q",))

        def _smap(fn):
            try:
                return shard_map(fn, mesh=mesh, in_specs=P("q"),
                                 out_specs=P("q"), check_rep=False)
            except TypeError:  # newer jax renamed the kwarg
                return shard_map(fn, mesh=mesh, in_specs=P("q"),
                                 out_specs=P("q"), check_vma=False)

        qx = ((np.arange(len(devs) * 4096) % 509) / 509.0 - 0.5) \\
            .astype(np.float32).reshape(len(devs), 4096)
        qz.reset_device_byte_counters()
        qout = np.asarray(jax.jit(_smap(
            lambda shard: hvd.allreduce(shard, axis_name="q")))(
                jnp.asarray(qx)))
        qraw, qenc = qz.device_byte_counters()
        qmean = np.broadcast_to(qx.mean(axis=0), qx.shape)
        if qdev != "demote":
            qcodec = qdev.split(":", 1)[0]
            assert qraw > 0 and qenc < qraw, (qraw, qenc)
            # int4's scale/2 is 127/7 ≈ 18x the int8 one; 2.0 covers it
            # with slack while staying far under the signal's magnitude.
            qbound = {"int4": 2.0}.get(qcodec, 0.5) / len(devs)
            qerr = float(np.max(np.abs(qout - qmean)))
            assert qerr < qbound, (qcodec, qerr, qbound)
        else:  # demote
            assert (qraw, qenc) == (0, 0), (qraw, qenc)
            import jax.lax as lax
            qplain = np.asarray(jax.jit(_smap(
                lambda shard: lax.pmean(shard, "q")))(jnp.asarray(qx)))
            np.testing.assert_array_equal(qout, qplain)

    # dplane axis: the gspmd data plane (HOROVOD_DATA_PLANE / the
    # DistributedOptimizer plane= knob) over the forced multi-device host
    # platform.  "gspmd" asserts the env-plumbed request reaches the
    # optimizer (selection counter moves) and a jitted train step runs;
    # "diff" trains the same problem under both planes and asserts parity
    # within fp32 reduction-order tolerance.
    dplane = os.environ.get("HVD_MATRIX_DPLANE", "off")
    if dplane != "off":
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        from horovod_tpu.ops import gspmd_plane as gp
        from horovod_tpu.optimizer import DistributedOptimizer

        devs = jax.devices()
        assert len(devs) >= 2, "dplane combo expects a forced multi-dev host"
        drs = np.random.RandomState(7)
        dx = drs.randn(8 * len(devs), 4).astype(np.float32)
        dy = drs.randn(8 * len(devs)).astype(np.float32)
        dp0 = {"w": np.zeros(4, np.float32), "b": np.float32(0.0)}

        def dloss(p, xs, ys):
            return jnp.mean((xs @ p["w"] + p["b"] - ys) ** 2)

        def train_gspmd(tx):
            mesh = gp.build_gspmd_mesh()
            xs = jax.device_put(jnp.asarray(dx),
                                NamedSharding(mesh, P(gp.BATCH_AXIS)))
            ys = jax.device_put(jnp.asarray(dy),
                                NamedSharding(mesh, P(gp.BATCH_AXIS)))
            p = jax.tree_util.tree_map(jnp.asarray, dp0)
            st = tx.init(p)

            @jax.jit
            def step(p, st, xs, ys):
                g = jax.grad(dloss)(p, xs, ys)
                u, st2 = tx.update(g, st, p)
                return optax.apply_updates(p, u), st2

            for _ in range(3):
                p, st = step(p, st, xs, ys)
            return p

        gp.reset_plane_counters()
        if dplane == "gspmd":
            # plane unset: HOROVOD_DATA_PLANE=gspmd must have ridden
            # env.py -> Config -> data_plane_default into the optimizer.
            pg = train_gspmd(DistributedOptimizer(optax.sgd(0.1)))
            dc = gp.plane_counters()
            assert dc.get("gspmd") == 1, dc
            assert np.isfinite(np.asarray(pg["w"])).all()
        else:  # diff: eager-vs-gspmd differential parity
            pg = train_gspmd(DistributedOptimizer(optax.sgd(0.1),
                                                  plane="gspmd"))
            emesh = Mesh(np.asarray(devs), ("dpx",))
            tx_e = DistributedOptimizer(optax.sgd(0.1), plane="eager",
                                        axis_name="dpx")

            def eshard(p, st, xs, ys):
                g = jax.grad(dloss)(p, xs, ys)
                u, st2 = tx_e.update(g, st, p)
                return optax.apply_updates(p, u), st2

            especs = dict(mesh=emesh, in_specs=(P(), P(), P("dpx"),
                                                P("dpx")),
                          out_specs=(P(), P()))
            try:
                esm = shard_map(eshard, check_rep=False, **especs)
            except TypeError:  # newer jax renamed the kwarg
                esm = shard_map(eshard, check_vma=False, **especs)
            estep = jax.jit(esm)
            pe = jax.tree_util.tree_map(jnp.asarray, dp0)
            ste = tx_e.init(pe)
            for _ in range(3):
                pe, ste = estep(pe, ste, jnp.asarray(dx), jnp.asarray(dy))
            np.testing.assert_allclose(np.asarray(pg["w"]),
                                       np.asarray(pe["w"]),
                                       rtol=2e-6, atol=1e-7)
            np.testing.assert_allclose(np.asarray(pg["b"]),
                                       np.asarray(pe["b"]),
                                       rtol=2e-6, atol=1e-7)

    # hloinspect axis: compiled-collective introspection — a gspmd-plane
    # train step through ops/hlo_inspect.instrument must yield a
    # non-empty inventory whose analytic byte totals match the live
    # counters exactly; "off" asserts HOROVOD_HLO_INSPECT=0 makes
    # instrument the identity (same object back, counters untouched).
    hli = os.environ.get("HVD_MATRIX_HLOINSPECT", "def")
    if hli != "def":
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from horovod_tpu.ops import gspmd_plane as gp
        from horovod_tpu.ops import hlo_inspect as hi
        from horovod_tpu.optimizer import DistributedOptimizer

        devs = jax.devices()
        assert len(devs) >= 2, "hloinspect combo expects a multi-dev host"
        hi.reset()
        hmesh = gp.build_gspmd_mesh()
        hn = hmesh.shape[gp.BATCH_AXIS] * 4
        hrs = np.random.RandomState(11)
        hx = jax.device_put(jnp.asarray(hrs.randn(hn, 4), jnp.float32),
                            NamedSharding(hmesh, P(gp.BATCH_AXIS)))
        hy = jax.device_put(jnp.asarray(hrs.randn(hn), jnp.float32),
                            NamedSharding(hmesh, P(gp.BATCH_AXIS)))
        hp = {"w": jnp.zeros((4,), jnp.float32)}
        htx = DistributedOptimizer(optax.sgd(0.1), plane="gspmd")
        hst = htx.init(hp)

        def hstep(p, st, xs, ys):
            def hl(p):
                return jnp.mean((xs @ p["w"] - ys) ** 2)
            g = jax.grad(hl)(p)
            u, st2 = htx.update(g, st, p)
            return optax.apply_updates(p, u), st2

        hbase = jax.jit(hstep)
        hwrapped = hi.instrument(hbase, label="matrix")
        if hli == "on":
            hp, hst = hwrapped(hp, hst, hx, hy)
            jax.block_until_ready(hp)
            hinvs = [i for i in hi.inventories() if i.label == "matrix"]
            assert hinvs, "gspmd trace yielded no collective inventory"
            hinv = hinvs[-1]
            assert hinv.collectives > 0, hinv.to_dict()
            hraw, hwire = hi.gspmd_byte_counters()
            assert (hinv.raw_bytes, hinv.wire_bytes) == (hraw, hwire), \
                (hinv.raw_bytes, hinv.wire_bytes, hraw, hwire)
        else:  # off: zero-overhead contract — the identity wrapper
            assert hwrapped is hbase, \
                "HOROVOD_HLO_INSPECT=0 must return the step unchanged"
            hp, hst = hwrapped(hp, hst, hx, hy)
            jax.block_until_ready(hp)
            assert hi.inventories() == [], "introspection off but recorded"
            assert hi.gspmd_byte_counters() == (0, 0)

    # flight axis: the always-on black box must have recorded the work
    # (ctrl frames exist at np>1 only; np=1 has no socket control plane).
    fl = os.environ.get("HOROVOD_FLIGHT_RECORDER", "")
    if fl == "1" and s > 1:
        fr = hvd.flight_record()
        assert fr.get("events"), fr
        assert fr.get("rank") == r, fr
        assert fr.get("types"), fr
    elif fl == "off":
        assert hvd.flight_record() == {}, "recorder off but ring non-empty"

    # migrate axis: a committed elastic state must land, bit-exact, on the
    # ring successors' shard stores via the data-plane replication path.
    if os.environ.get("HVD_MATRIX_MIGRATE") == "on" and s > 1:
        import pickle
        from horovod_tpu.elastic import migrate as mig

        est = hvd.elastic.ObjectState(
            step=0, w=np.full(4, float(r), np.float32))
        est.step = 1
        est.commit()
        st = mig.store()
        assert st.own is not None and st.own.owner == r, (r, st.own)
        assert len(st.peers) >= min(2, s - 1), sorted(st.peers)
        pred = (r - 1) % s
        recs = [p for p in st.peers.values() if p.owner == pred]
        assert recs, sorted(st.peers)
        attrs = pickle.loads(recs[0].data)["attrs"]
        assert attrs["step"] == 1, attrs
        np.testing.assert_array_equal(
            attrs["w"], np.full(4, float(pred), np.float32))

    # trace axis: the causal step ring must carry the work done above —
    # completed steps with wall-clock bounds and the 5-phase breakdown,
    # plus the coordinator's fleet attribution at np>1.
    tr = os.environ.get("HOROVOD_STEP_TRACE", "")
    if tr == "1":
        t = hvd.step_trace()
        assert t.get("completed", 0) > 0, t
        assert t["phases"] == ["negotiation_wait", "fusion", "ring",
                               "fence", "idle"], t["phases"]
        assert t["steps"] and all(len(row) == 9 and row[2] >= row[1] > 0
                                  for row in t["steps"]), t["steps"][:3]
        assert any(sum(row[3:8]) > 0 for row in t["steps"]), t["steps"][:3]
        if r == 0 and s > 1:
            assert t["fleet"], "coordinator recorded no fleet attribution"
    elif tr == "0":
        assert hvd.step_trace() == {}, "tracing off but ring non-empty"

    # metrics axis: the registry must have seen the work done above.
    if os.environ.get("HOROVOD_METRICS") == "1":
        m = hvd.metrics()
        assert m.get("enabled"), m
        assert m["counters"]["cycle_count"] > 0, m["counters"]
        assert m["histograms"]["negotiation_wait_us"]["count"] > 0, \
            m["histograms"]
        assert hvd.metrics_prometheus().startswith("# HELP")

    # fleet axis: the v11 sketch sections must have landed true fleet
    # histograms on the coordinator, and the history endpoint must serve
    # the fleethistory-v1 payload; "off" keeps both surfaces empty.
    ft = os.environ.get("HOROVOD_FLEET_TELEMETRY", "")
    if ft == "1":
        if r == 0:
            fleet = hvd.metrics().get("fleet") or {}
            assert fleet.get("negotiation_wait_us", {}).get("count", 0) > 0, \
                fleet
            fh = hvd.fleet_history()
            assert fh.get("schema") == "fleethistory-v1", fh
            assert fh.get("tiers"), fh
    elif ft == "0":
        assert "fleet" not in (hvd.metrics() or {}), \
            "fleet telemetry off but metrics carries a fleet section"
        assert hvd.fleet_history() == {}, \
            "fleet telemetry off but history non-empty"

    hvd.barrier()
    hvd.shutdown()
    print(f"WORKLOAD-OK rank={r}", flush=True)
""")


TORCH_WORKLOAD = textwrap.dedent("""
    import os
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    import torch
    import horovod_tpu.torch as hvd

    hvd.init(build_mesh=False)
    r, s = hvd.rank(), hvd.size()

    x = torch.full((33,), float(r + 1))
    total = s * (s + 1) / 2.0
    np.testing.assert_allclose(
        hvd.allreduce(x, op=hvd.Sum, name="m.sum").numpy(), total)
    np.testing.assert_allclose(
        hvd.allreduce_(x.clone(), op=hvd.Average, name="m.avg").numpy(),
        total / s)

    # fusion sweep through the grad-hook shape: many small in-place ops
    ts = [torch.full((8,), float(i + r)) for i in range(40)]
    handles = [hvd.allreduce_async_(t, op=hvd.Sum, name=f"m.f.{i}")
               for i, t in enumerate(ts)]
    for i, h in enumerate(handles):
        hvd.synchronize(h)
        np.testing.assert_allclose(ts[i].numpy(),
                                   s * i + s * (s - 1) / 2.0)

    # cache steady state
    for it in range(20):
        out = hvd.allreduce(torch.full((16,), float(r)), op=hvd.Sum,
                            name="m.cached")
        np.testing.assert_allclose(out.numpy(), s * (s - 1) / 2.0)

    # ragged allgather + broadcast + equal-splits alltoall
    g = hvd.allgather(torch.full((r + 1, 2), float(r)), name="m.ag")
    assert tuple(g.shape) == (s * (s + 1) // 2, 2), g.shape
    for root in range(s):
        out = hvd.broadcast(torch.full((5,), float(r), dtype=torch.float64),
                            root_rank=root, name=f"m.bc.{root}")
        np.testing.assert_allclose(out.numpy(), float(root))
    data = (torch.arange(2 * s, dtype=torch.float32) + 10 * r).reshape(-1, 1)
    out, _ = hvd.alltoall(data, splits=[2] * s, name="m.a2a")
    assert tuple(out.shape) == (2 * s, 1)

    # big fp32 payload above the wire-compression floor (see jax workload).
    wire = os.environ.get("HOROVOD_WIRE_COMPRESSION", "none")
    if "=" in wire:  # per-plane syntax: the host ring takes the host= entry
        wire = dict(kv.split("=", 1)
                    for kv in wire.split(",")).get("host", "none")
    wtol = {"bf16": dict(rtol=0.04, atol=1e-3),
            "int8": dict(rtol=0.05, atol=6.0)}.get(wire, dict(rtol=1e-6))
    big = torch.remainder(torch.arange(1 << 16, dtype=torch.float32),
                          251.0) + r
    wexp = sum((np.arange(1 << 16) % 251 + rr).astype(np.float32)
               for rr in range(s))
    np.testing.assert_allclose(
        hvd.allreduce(big, op=hvd.Sum, name="m.wire").numpy(), wexp, **wtol)

    hvd.barrier()
    hvd.shutdown()
    print(f"WORKLOAD-OK rank={r}", flush=True)
""")


def combos(quick: bool):
    cores = ["native", "purepy"]
    nps = [1, 2, 3]
    fusion = ["on", "off"]
    cache = ["on", "off"]
    planes = ["shm", "tcp", "tcp0", "hier"]
    wires = ["none", "bf16", "int8"]
    if quick:
        # One covering set instead of the full product (every axis value
        # appears; hier+none pairing is covered by tests/parallel).  The
        # metrics axis stays "off" here — its on-combos live in the full
        # set and tests/parallel/test_metrics.py covers the plane directly.
        yield ("jax", "native", 3, "on", "on", "shm", "none", "off")
        # Same-host links: the coordinator must demote the codec (knob
        # harmless, results exact).
        yield ("jax", "native", 2, "off", "off", "tcp", "bf16", "off")
        yield ("jax", "native", 3, "on", "off", "tcp0", "none", "off")
        yield ("jax", "native", 3, "on", "on", "hier", "bf16", "off")
        yield ("jax", "native", 3, "on", "off", "hier", "int8", "off")
        # ctrl_tree axis: the one quick on-combo (2 fake hosts via hier)
        # plus the forced depth-3 combo (3 fake hosts; the v12 chain
        # coordinator <- super <- leader carries every frame).
        yield ("jax", "native", 3, "on", "on", "hier", "none", "off", "on")
        yield ("jax", "native", 3, "on", "on", "hier", "none", "off", "d3")
        # flight axis: the one quick recorder-on combo.
        yield ("jax", "native", 3, "on", "on", "shm", "none", "off", "auto",
               "on")
        # autopilot axis: the one quick on-combo — elastic driver + policy
        # thread over a healthy fleet; zero decisions, same results.
        yield ("jax", "native", 3, "on", "on", "shm", "none", "off", "auto",
               "def", "on")
        # qdev axis: the quick device-codec combos (forced 4-dev host) —
        # the int8 baseline plus one new-codec/new-schedule row.
        yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
               "def", "off", "int8")
        yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
               "def", "off", "int4:bidi")
        # dplane axis: the one quick gspmd on-combo — HOROVOD_DATA_PLANE
        # plumbed env -> Config -> optimizer over a forced 4-dev host.
        yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
               "def", "off", "off", "off", "def", "def", "gspmd")
        # hloinspect axis: the one quick on-combo — a gspmd trace's
        # inventory matching the live byte counters bit-for-bit.
        yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
               "def", "off", "off", "off", "def", "def", "off", "on")
        # migrate axis: the one quick on-combo — peer-shard replication
        # rides a committed elastic state over the shm data plane.
        yield ("jax", "native", 3, "on", "on", "shm", "none", "off", "auto",
               "def", "off", "off", "on")
        # trace axis: the one quick on-combo — the step ring populated
        # with fleet attribution on the coordinator.
        yield ("jax", "native", 3, "on", "on", "shm", "none", "off", "auto",
               "def", "off", "off", "off", "on")
        # fleet axis: the one quick on-combo — v11 sketch sections summed
        # into coordinator fleet histograms + the history payload served.
        yield ("jax", "native", 3, "on", "on", "shm", "none", "on", "auto",
               "def", "off", "off", "off", "def", "on")
        yield ("jax", "native", 1, "on", "off", "shm", "none", "off")
        yield ("jax", "purepy", 1, "off", "on", "shm", "none", "off")
        yield ("torch", "native", 2, "on", "on", "shm", "none", "off")
        yield ("torch", "native", 3, "off", "off", "tcp", "none", "off")
        yield ("torch", "purepy", 1, "on", "on", "shm", "none", "off")
        return
    for core, np_, f, c, p, w in itertools.product(cores, nps, fusion,
                                                   cache, planes, wires):
        if core == "purepy" and np_ > 1:
            continue  # pure-python core is single-process by contract
        if np_ == 1 and p != "shm":
            continue  # no data plane at np=1; plane axis is meaningless
        if p == "hier" and np_ < 3:
            continue  # 2 ranks / 2 fake hosts has no multi-rank host
        if w != "none" and (p != "hier" or core != "native"):
            continue  # codec engages only on cross-host hops (leader ring)
        yield ("jax", core, np_, f, c, p, w, "off")
    # Demotion coverage: codec requested on an all-local flat ring.
    yield ("jax", "native", 2, "on", "on", "tcp", "bf16", "off")
    yield ("jax", "native", 3, "on", "on", "shm", "int8", "off")
    # Metrics-axis coverage: registry populated across controller shapes
    # (local np=1, socket, hierarchical) without disturbing the results.
    yield ("jax", "native", 1, "on", "on", "shm", "none", "on")
    yield ("jax", "native", 3, "on", "on", "shm", "none", "on")
    yield ("jax", "native", 3, "off", "off", "tcp", "none", "on")
    yield ("jax", "native", 3, "on", "on", "hier", "bf16", "on")
    # Control-tree axis: v9 leader tree forced on over fake hosts ("auto"
    # stays flat below np=8), with caching/fusion/metrics variation, plus
    # a single-host demotion row (tree=on without multiple hosts must
    # quietly stay flat and change nothing).
    yield ("jax", "native", 3, "on", "on", "hier", "none", "off", "on")
    yield ("jax", "native", 3, "off", "off", "hier", "none", "on", "on")
    yield ("jax", "native", 3, "on", "on", "hier", "bf16", "off", "on")
    yield ("jax", "native", 3, "on", "on", "tcp", "none", "off", "on")
    yield ("torch", "native", 3, "on", "on", "hier", "none", "off", "on")
    # Adaptive-depth (v12) rows: the forced depth-3 chain with metrics on
    # (telemetry sketches relayed through the super-leader) and with
    # caching/fusion off (every cycle renegotiates through two hops).
    yield ("jax", "native", 3, "on", "on", "hier", "none", "on", "d3")
    yield ("jax", "native", 3, "off", "off", "hier", "none", "off", "d3")
    # Flight-recorder axis: explicit on (black box populated) across plane
    # shapes including the v9 tree, and explicit off (flight_record == {}).
    yield ("jax", "native", 3, "on", "on", "shm", "none", "off", "auto",
           "on")
    yield ("jax", "native", 3, "off", "off", "tcp", "none", "on", "auto",
           "on")
    yield ("jax", "native", 3, "on", "on", "hier", "none", "off", "on",
           "on")
    yield ("jax", "native", 3, "on", "on", "shm", "none", "off", "auto",
           "off")
    # Autopilot axis: policy thread over a healthy fleet (no decisions),
    # with and without the flat-TCP plane; the adversarial (straggling)
    # path is the autopilot-np4 check row.
    yield ("jax", "native", 3, "on", "on", "shm", "none", "off", "auto",
           "def", "on")
    yield ("jax", "native", 3, "off", "off", "tcp", "none", "off", "auto",
           "def", "on")
    # qdev axis: in-jit device-plane codec over a forced 4-device host
    # platform — engagement (counters move, bounded error), purepy parity
    # (the device ring is pure jax; it must not care which core runs the
    # host plane), one cross-plane combo (host bf16 leader ring + device
    # int8 ring in the same process), and the min-bytes demotion (codec
    # configured but cold, bit-identical result).
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "int8")
    yield ("jax", "purepy", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "int8")
    yield ("jax", "native", 3, "on", "on", "hier", "bf16", "off", "auto",
           "def", "off", "int8")
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "demote")
    # The new codecs and schedules: int4 (nibble-packed, coarser bound),
    # int8g (two-level scales), and the schedule suffix pinning the bidi
    # and torus rings — 4 forced devices factor as 2x2, exercising the
    # torus demotion-to-bidi rule as well as the explicit bidi path.
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "int4")
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "int8g")
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "int8:bidi")
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "int4:torus")
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "int8g:ring")
    # dplane axis: the gspmd data plane over a forced 4-device host — the
    # env-plumbed engagement row (HOROVOD_DATA_PLANE=gspmd reaches the
    # optimizer, selection counter moves) and the eager-vs-gspmd
    # differential row (same problem trained under both calling
    # conventions, parity within fp32 reduction-order tolerance).
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "off", "off", "def", "def", "gspmd")
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "off", "off", "def", "def", "diff")
    # hloinspect axis: compiled-collective introspection on (a gspmd
    # trace's inventory matches the live counters exactly) and explicitly
    # off (instrument is the identity, counters stay zero).
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "off", "off", "def", "def", "off", "on")
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "off", "off", "def", "def", "off", "off")
    # Migrate axis: replication across the plane shapes the shards actually
    # ride in production — shm, the flat TCP ring, and the hier topology —
    # plus a metrics-on row so the hvd_migrate_* counters are scraped live.
    yield ("jax", "native", 3, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "off", "on")
    yield ("jax", "native", 2, "on", "on", "tcp", "none", "off", "auto",
           "def", "off", "off", "on")
    yield ("jax", "native", 3, "on", "on", "hier", "none", "on", "auto",
           "def", "off", "off", "on")
    # Trace axis: explicit on across controller shapes — local np=1, the
    # socket controller, and the v9 tree over fake hosts — plus a
    # metrics-on row (the CYCLE trailer carries both the metrics and the
    # step-trace sections, marker 2) and explicit off (step_trace == {}).
    yield ("jax", "native", 1, "on", "on", "shm", "none", "off", "auto",
           "def", "off", "off", "off", "on")
    yield ("jax", "native", 3, "on", "on", "shm", "none", "on", "auto",
           "def", "off", "off", "off", "on")
    yield ("jax", "native", 3, "on", "on", "hier", "none", "off", "on",
           "def", "off", "off", "off", "on")
    yield ("jax", "native", 3, "off", "off", "tcp", "none", "off", "auto",
           "def", "off", "off", "off", "off")
    # Fleet-telemetry axis: v11 sketch sections across controller shapes —
    # flat shm, the flat TCP ring, and the v9 leader tree (host-summed
    # sketches up the tree) — plus explicit off (no fleet section in the
    # metrics dump, empty history payload).
    yield ("jax", "native", 3, "on", "on", "shm", "none", "on", "auto",
           "def", "off", "off", "off", "def", "on")
    yield ("jax", "native", 3, "off", "off", "tcp", "none", "on", "auto",
           "def", "off", "off", "off", "def", "on")
    yield ("jax", "native", 3, "on", "on", "hier", "none", "on", "on",
           "def", "off", "off", "off", "def", "on")
    yield ("jax", "native", 3, "on", "on", "shm", "none", "on", "auto",
           "def", "off", "off", "off", "def", "off")
    # Torch-binding covering subset (same core spine underneath; a full
    # product would double the wall time for little marginal coverage).
    yield ("torch", "native", 2, "on", "on", "shm", "none", "off")
    yield ("torch", "native", 2, "off", "off", "tcp", "none", "off")
    yield ("torch", "native", 2, "on", "off", "tcp0", "none", "off")
    yield ("torch", "native", 3, "on", "on", "tcp", "none", "off")
    yield ("torch", "native", 3, "off", "on", "shm", "none", "off")
    yield ("torch", "native", 3, "on", "on", "hier", "none", "off")
    yield ("torch", "native", 3, "on", "on", "hier", "bf16", "off")
    yield ("torch", "native", 3, "on", "on", "hier", "int8", "off")
    yield ("torch", "native", 1, "on", "on", "shm", "none", "off")
    yield ("torch", "purepy", 1, "on", "on", "shm", "none", "off")


def checks(quick: bool):
    """Non-workload rows: static analysis, the sanitizer builds, and the
    fault axis.

    Yields (name, [argv, ...], cwd[, timeout]) — the argvs run in
    sequence, all must exit 0.  `lint` is pure text analysis (no build)
    and belongs in the quick set, as does `fault-spec` (the parser
    contract the quick chaos story rests on); the sanitizer rows compile
    the whole controller stack (~1 min each on a laptop core), and the
    chaos/np=4 fault rows exercise whole-job collapse, so full matrix
    only.
    """
    yield ("lint",
           [[sys.executable, os.path.join(REPO, "tools", "hvd_lint.py")]],
           REPO)
    # The concurrency-discipline passes also run standalone so a failure
    # is attributed to the discipline that broke (atomic memory-order
    # audit / lock-order cycles / async-signal-safety), not just "lint".
    for cpass in ("atomic", "lockorder", "sigsafe"):
        yield (f"lint-{cpass}",
               [[sys.executable, os.path.join(REPO, "tools", "hvd_lint.py"),
                 "--only", cpass]],
               REPO)
    yield ("fault-spec",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "single", "test_fault_spec.py")]],
           REPO)
    if quick:
        return
    for target in ("asan_selftest", "ubsan_selftest"):
        yield (target.split("_")[0],
               [["make", target], [os.path.join(CPP_DIR, target)]],
               CPP_DIR)
    yield ("chaos",
           [["make", "chaos_selftest"],
            [os.path.join(CPP_DIR, "chaos_selftest")]],
           CPP_DIR)
    # Whole-job collapse measured from Python: injected rank death within
    # the abort bound, corrupt-tag fail-fast, elastic --fault-inject
    # recovery.  Three multi-process scenarios: give them their own cap.
    yield ("fault-np4",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "parallel", "test_fault_injection.py")]],
           REPO, 600.0)
    # Chaos-postmortem: an injected rank death must leave a complete
    # merged postmortem.json (right culprit, a pre-abort digest from every
    # survivor) without stretching the abort bound.
    yield ("postmortem-np4",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "parallel", "test_postmortem.py")]],
           REPO, 600.0)
    # Hands-off autopilot chaos loop: one rank persistently straggles
    # (injected delay) -> the autopilot detects, attributes, evicts, the
    # elastic driver recovers at smaller np, and blacklist expiry
    # re-admits the host -- asserted end to end with zero human input.
    yield ("autopilot-np4",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "parallel", "test_autopilot.py")]],
           REPO, 600.0)
    # Zero-downtime migration chaos: injected rank death -> fast abort ->
    # re-form np=3 resuming bit-identically from peer shards (zero
    # checkpoint reads) -> blacklist-expiry re-grow to np=4; plus the
    # degraded path (replicas lost -> sharded-checkpoint fallback).
    yield ("migration-np4",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "parallel", "test_migration.py")]],
           REPO, 600.0)
    # Live cockpit + critical path at np=4: an injected coordinator-recv
    # delay against rank 3 must be attributed to rank 3 / negotiation_wait
    # by BOTH surfaces — the live /state snapshot queried mid-run and
    # tools/critical_path.py over the shutdown step-trace dumps.
    yield ("cockpit-np4",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "parallel", "test_step_trace.py")]],
           REPO, 600.0)
    # Anomaly sentinel end to end at np=4: a persistent injected delay on
    # one rank must raise a sentinel anomaly (flight type 15 + the
    # autopilot journal) naming that rank strictly BEFORE the
    # eviction-windows rule can fire, with /history showing the
    # inflection; includes the fleet bucket-exactness assertions.
    yield ("sentinel-np4",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "parallel", "test_fleet_telemetry.py")]],
           REPO, 600.0)
    # np=256 in-process control-plane soak: flat vs v9 tree coordinator
    # message counts (>= 8x cut at 256 ranks / 16 fake hosts) plus the
    # sharded rendezvous acceptors under the full HELLO herd.
    yield ("ctrl-soak",
           [["make", "ctrl_soak_selftest"],
            [os.path.join(CPP_DIR, "ctrl_soak_selftest")]],
           CPP_DIR, 600.0)
    # np=1024 / 64-fake-host pod-scale soak (v12): the auto-grown
    # three-level tree cuts coordinator inbound to O(fanout) (17 msgs per
    # cycle vs 1023 flat), bucket-exact sketch merges, and the chaos arms
    # (super-leader death, mid-level leader death, adaptive-depth site)
    # abort within the bound naming the right culprit.
    yield ("ctrl-soak-1024",
           [["make", "ctrl_soak_selftest"],
            ["env", "CTRL_SOAK_NP=1024", "CTRL_SOAK_HOSTS=64",
             os.path.join(CPP_DIR, "ctrl_soak_selftest")]],
           CPP_DIR, 600.0)
    # np=8 fake-host end-to-end: tree-vs-flat collective/attribution
    # parity and leader-death abort bounds.
    yield ("ctrl-np8",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "parallel", "test_ctrl_tree_np8.py")]],
           REPO, 600.0)
    # np=8 adaptive-depth end-to-end: flat == depth-2 == depth-3 parity
    # and the super-leader-death abort bound (v12).
    yield ("ctrl-depth-np8",
           [[sys.executable, "-m", "pytest", "-q",
             os.path.join("tests", "parallel",
                          "test_ctrl_tree_depth.py")]],
           REPO, 600.0)


def run_check(cmds, cwd: str, timeout: float) -> tuple:
    t0 = time.monotonic()
    for cmd in cmds:
        try:
            proc = subprocess.run(cmd, cwd=cwd, capture_output=True,
                                  text=True, timeout=timeout)
        except subprocess.TimeoutExpired as exc:
            return False, time.monotonic() - t0, f"timeout: {exc}"
        if proc.returncode != 0:
            return False, time.monotonic() - t0, \
                (proc.stdout + proc.stderr)[-800:]
    return True, time.monotonic() - t0, ""


def run_combo(core: str, np_: int, fusion: str, cache: str,
              plane: str, wire: str, metrics: str, tree: str, flight: str,
              autopilot: str, qdev: str, migrate: str, trace: str,
              fleet: str, dplane: str, hloinspect: str, script: str,
              timeout: float) -> tuple:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # The plane axis must own this knob: an ambient setting would
    # silently collapse the pipelined-vs-legacy distinction.
    env.pop("HOROVOD_RING_CHUNK_BYTES", None)
    env.pop("HOROVOD_HIERARCHICAL_ALLREDUCE", None)
    env.pop("HOROVOD_HIER_FAKE_HOSTS", None)
    # Same for the wire axis: ambient codec settings would skew both the
    # exact asserts (wire=none combos) and the demotion combos.
    env.pop("HOROVOD_WIRE_COMPRESSION", None)
    env.pop("HOROVOD_WIRE_COMPRESSION_MIN_BYTES", None)
    # And the metrics axis: an ambient HOROVOD_METRICS_FILE would make
    # every combo write snapshot files (and "off" combos assert nothing).
    env.pop("HOROVOD_METRICS", None)
    env.pop("HOROVOD_METRICS_FILE", None)
    env.pop("HOROVOD_METRICS_INTERVAL", None)
    # An ambient fault-injection spec would sabotage every workload combo
    # (that's its job); faults belong to the dedicated check rows only.
    env.pop("HOROVOD_FAULT_INJECT", None)
    # The ctrl_tree axis owns the control-plane topology knobs (v12:
    # depth/fanout shape the tree, so ambient values would change every
    # combo's frame routing).
    env.pop("HOROVOD_CONTROL_TREE", None)
    env.pop("HOROVOD_CONTROL_TREE_DEPTH", None)
    env.pop("HOROVOD_CTRL_TREE_FANOUT", None)
    # The flight axis owns the recorder knobs; an ambient postmortem dir
    # would scatter crash bundles on every combo failure.
    env.pop("HOROVOD_FLIGHT_RECORDER", None)
    env.pop("HOROVOD_FLIGHT_RECORDER_SLOTS", None)
    env.pop("HOROVOD_POSTMORTEM_DIR", None)
    # The autopilot axis owns the policy-engine knob (and its port is
    # per-generation driver state, never ambient).
    env.pop("HOROVOD_AUTOPILOT", None)
    env.pop("HOROVOD_AUTOPILOT_PORT", None)
    # The migrate axis owns the replication knobs: an ambient setting
    # would make every combo pay the replication alltoall per commit.
    env.pop("HOROVOD_MIGRATE_REPLICAS", None)
    env.pop("HOROVOD_MIGRATE_INTERVAL_STEPS", None)
    # The trace axis owns the step-trace knobs, and the cockpit binds a
    # listener — an ambient HOROVOD_COCKPIT would open a port per combo.
    env.pop("HOROVOD_STEP_TRACE", None)
    env.pop("HOROVOD_STEP_TRACE_SLOTS", None)
    env.pop("HOROVOD_COCKPIT", None)
    env.pop("HOROVOD_COCKPIT_PORT", None)
    # The fleet axis owns the v11 telemetry knobs; an ambient sentinel
    # threshold would skew the anomaly-free expectation of "on" combos.
    env.pop("HOROVOD_FLEET_TELEMETRY", None)
    env.pop("HOROVOD_SENTINEL_ZSCORE", None)
    # The dplane axis owns the data-plane knob: an ambient gspmd request
    # would reroute every combo's optimizer path.
    env.pop("HOROVOD_DATA_PLANE", None)
    # The hloinspect axis owns the introspection knob: "off" combos
    # assert the identity-wrapper contract an ambient =1 would break.
    env.pop("HOROVOD_HLO_INSPECT", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if core == "purepy":
        env["HVD_TPU_PURE_PY"] = "1"
    if fusion == "off":
        env["HOROVOD_FUSION_THRESHOLD"] = "0"
    if cache == "off":
        env["HOROVOD_CACHE_CAPACITY"] = "0"
    if plane in ("tcp", "tcp0"):
        env["HOROVOD_SHM_DISABLE"] = "1"
    if plane == "tcp0":
        env["HOROVOD_RING_CHUNK_BYTES"] = "0"  # legacy whole-segment frames
    if plane == "hier":
        # Two fake hosts carved out of the rank space: block partition, so
        # np=3 gives hosts {0,1} + {2} — the smallest hierarchical topology.
        env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
        env["HOROVOD_HIER_FAKE_HOSTS"] = "2"
    # The wire and qdev axes share one knob: bare codec = host plane only,
    # per-plane syntax once the device ring is in play.  A qdev value is
    # "<codec>[:<schedule>]" or "demote" (int8 under a prohibitive floor).
    wire_planes = []
    if wire != "none":
        wire_planes.append(f"host={wire}" if qdev != "off" else wire)
    if qdev != "off":
        qcodec, _, qsched = qdev.partition(":")
        if qcodec == "demote":
            qcodec = "int8"
        wire_planes.append(f"device={qcodec}")
        if qsched:
            env["HOROVOD_DEVICE_SCHEDULE"] = qsched
    if wire_planes:
        env["HOROVOD_WIRE_COMPRESSION"] = ",".join(wire_planes)
    if qdev != "off":
        env["HVD_MATRIX_QDEV"] = qdev
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=4")
        env["HOROVOD_WIRE_COMPRESSION_MIN_BYTES"] = str(
            (1 << 30) if qdev == "demote" else 4096)
    if metrics == "on":
        env["HOROVOD_METRICS"] = "1"
    if tree == "d3":
        # Forced three-level tree needs >= 3 leaders: three single-rank
        # fake hosts give the chain coordinator <- super <- leaf leader.
        env["HOROVOD_CONTROL_TREE"] = "on"
        env["HOROVOD_CONTROL_TREE_DEPTH"] = "3"
        env["HOROVOD_HIER_FAKE_HOSTS"] = "3"
    elif tree != "auto":
        env["HOROVOD_CONTROL_TREE"] = tree
    if flight == "on":
        env["HOROVOD_FLIGHT_RECORDER"] = "1"
    elif flight == "off":
        env["HOROVOD_FLIGHT_RECORDER"] = "off"
    if autopilot == "on":
        # Routes the launch through the elastic driver with the policy
        # thread attached (launch.py reads the env fallback); the driver
        # forces HOROVOD_METRICS=1 on the workers.
        env["HOROVOD_AUTOPILOT"] = "1"
    if migrate == "on":
        env["HVD_MATRIX_MIGRATE"] = "on"
        env["HOROVOD_MIGRATE_REPLICAS"] = "2"
        env["HOROVOD_MIGRATE_INTERVAL_STEPS"] = "1"
    if trace == "on":
        env["HOROVOD_STEP_TRACE"] = "1"
    elif trace == "off":
        env["HOROVOD_STEP_TRACE"] = "0"
    if dplane != "off":
        env["HVD_MATRIX_DPLANE"] = dplane
        if "xla_force_host_platform_device_count" not in \
                env.get("XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count=4")
        if dplane == "gspmd":
            env["HOROVOD_DATA_PLANE"] = "gspmd"
    if hloinspect != "def":
        env["HVD_MATRIX_HLOINSPECT"] = hloinspect
        env["HOROVOD_HLO_INSPECT"] = "1" if hloinspect == "on" else "0"
        if "xla_force_host_platform_device_count" not in \
                env.get("XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count=8")
    if fleet == "on":
        # The fleet plane rides the metrics registry: sketches encode the
        # local histograms, so the combo forces the metrics plane on.
        env["HOROVOD_FLEET_TELEMETRY"] = "1"
        env["HOROVOD_METRICS"] = "1"
    elif fleet == "off":
        env["HOROVOD_FLEET_TELEMETRY"] = "0"
    if np_ == 1:
        cmd = [sys.executable, script]
    else:
        cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
               "-np", str(np_), sys.executable, script]
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired as exc:
        return False, time.monotonic() - t0, f"timeout: {exc}"
    ok = proc.returncode == 0 and \
        proc.stdout.count("WORKLOAD-OK") == np_
    detail = "" if ok else (proc.stdout + proc.stderr)[-800:]
    return ok, time.monotonic() - t0, detail


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="covering subset instead of the full product")
    ap.add_argument("--timeout", type=float, default=180.0)
    args = ap.parse_args()

    failures = []
    for row in checks(args.quick):
        name, cmds, cwd = row[:3]
        timeout = row[3] if len(row) > 3 else args.timeout
        ok, dt, detail = run_check(cmds, cwd, timeout)
        label = f"check={name}"
        print(f"{'PASS' if ok else 'FAIL'}  {label}  ({dt:5.1f}s)",
              flush=True)
        if not ok:
            failures.append((label, detail))
    with tempfile.TemporaryDirectory() as td:
        scripts = {}
        for binding, text in (("jax", WORKLOAD), ("torch", TORCH_WORKLOAD)):
            scripts[binding] = os.path.join(td, f"workload_{binding}.py")
            with open(scripts[binding], "w") as f:
                f.write(text)
        for combo in combos(args.quick):
            if len(combo) == 8:  # rows predating the ctrl_tree axis
                combo = combo + ("auto",)
            if len(combo) == 9:  # rows predating the flight axis
                combo = combo + ("def",)
            if len(combo) == 10:  # rows predating the autopilot axis
                combo = combo + ("off",)
            if len(combo) == 11:  # rows predating the qdev axis
                combo = combo + ("off",)
            if len(combo) == 12:  # rows predating the migrate axis
                combo = combo + ("off",)
            if len(combo) == 13:  # rows predating the trace axis
                combo = combo + ("def",)
            if len(combo) == 14:  # rows predating the fleet axis
                combo = combo + ("def",)
            if len(combo) == 15:  # rows predating the dplane axis
                combo = combo + ("off",)
            if len(combo) == 16:  # rows predating the hloinspect axis
                combo = combo + ("def",)
            (binding, core, np_, fusion, cache, plane, wire, metrics,
             tree, flight, autopilot, qdev, migrate, trace, fleet,
             dplane, hloinspect) = combo
            label = (f"bind={binding:<5} core={core:<7} np={np_} "
                     f"fusion={fusion:<3} cache={cache:<3} plane={plane:<4} "
                     f"wire={wire:<4} metrics={metrics:<3} tree={tree:<4} "
                     f"flight={flight:<4} ap={autopilot} qdev={qdev} "
                     f"mig={migrate} trace={trace} fleet={fleet} "
                     f"dp={dplane} hlo={hloinspect}")
            ok, dt, detail = run_combo(core, np_, fusion, cache, plane,
                                       wire, metrics, tree, flight,
                                       autopilot, qdev, migrate, trace,
                                       fleet, dplane, hloinspect,
                                       script=scripts[binding],
                                       timeout=args.timeout)
            print(f"{'PASS' if ok else 'FAIL'}  {label}  ({dt:5.1f}s)",
                  flush=True)
            if not ok:
                failures.append((label, detail))
    for label, detail in failures:
        print(f"\n--- {label} ---\n{detail}", file=sys.stderr)
    print(f"\n{'ALL PASS' if not failures else f'{len(failures)} FAILED'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
