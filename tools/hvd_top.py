#!/usr/bin/env python
"""hvd_top — live terminal cockpit for a running horovod_tpu job.

Polls the coordinator's loopback cockpit endpoint (HOROVOD_COCKPIT=1, rank
0) and redraws a one-screen dashboard:

- step time sparkline over the last-N completed steps (sum of the fleet's
  phase microseconds per step),
- a stacked phase bar showing where the fleet's time went
  (negotiation-wait / fusion / ring / fence / idle) with the dominant phase
  called out,
- per-rank skew: each rank's announce lag on the latest step, so the
  straggler is visible at a glance,
- the per-tenant (process-set) QoS table and migration counters,
- the fleet-telemetry long-horizon panel (/history): step-p99 and goodput
  sparklines per downsampling tier plus the anomaly sentinel's recent log.

Every panel degrades instead of crashing: a /state snapshot without
step-trace fields (plane off, old runtime) dims the step panels, and a
missing or empty /history dims the long-horizon panel.

Two tail modes ride the same endpoint: ``--events`` follows the /events
SSE stream and prints one line per step / runtime instant (reconnecting
across elastic re-formations — the driver keeps the port stable), and
``--once``/``--json`` print a single snapshot for scripts and tests.

The endpoint is loopback-only; run hvd_top on the coordinator host (or
through an ssh tunnel: ``ssh -L 8787:127.0.0.1:<port> coord-host``).

Usage:
  python tools/hvd_top.py --port 8787
  python tools/hvd_top.py --port 8787 --events
  python tools/hvd_top.py --port 8787 --once
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request
from typing import Dict, List, Optional

SPARK = "▁▂▃▄▅▆▇█"
# One glyph + ANSI color per phase, in the wire order of
# cpp/step_trace.cc's kStepPhaseNames.
PHASE_GLYPHS = {
    "negotiation_wait": ("N", "\x1b[33m"),   # yellow — waiting on peers
    "fusion": ("F", "\x1b[35m"),             # magenta — packing buffers
    "ring": ("R", "\x1b[32m"),               # green — bytes moving
    "fence": ("B", "\x1b[36m"),              # cyan — shm barrier
    "idle": ("I", "\x1b[90m"),               # grey — nothing enqueued
}
RESET = "\x1b[0m"
DIM = "\x1b[2m"


def _dim(text: str, color: bool) -> str:
    return (DIM + text + RESET) if color else text


def fetch_json(host: str, port: int, path: str, timeout: float = 3.0):
    url = f"http://{host}:{port}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def sparkline(values: List[float]) -> str:
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    return "".join(SPARK[int((v - lo) / span * (len(SPARK) - 1))]
                   for v in values)


def stacked_bar(totals: Dict[str, int], width: int,
                color: bool) -> str:
    """One horizontal bar, each phase's share in its glyph (and color)."""
    total = sum(totals.values())
    if total <= 0:
        return "-" * width
    out = []
    used = 0
    items = [(p, us) for p, us in totals.items() if us > 0]
    for i, (phase, us) in enumerate(items):
        n = (width - used if i == len(items) - 1
             else max(1, round(us / total * width)))
        n = min(n, width - used)
        glyph, tint = PHASE_GLYPHS.get(phase, ("?", ""))
        out.append((tint + glyph * n + RESET) if color else glyph * n)
        used += n
        if used >= width:
            break
    return "".join(out)


def skew_lines(lag_us: List[int], width: int = 30) -> List[str]:
    """One bar per rank, scaled to the worst lag on the latest step."""
    if not lag_us:
        return []
    worst = max(lag_us) or 1
    lines = []
    for r, lag in enumerate(lag_us):
        n = int(lag / worst * width)
        mark = " <- straggler" if lag == worst and worst > 0 and \
            len(lag_us) > 1 else ""
        lines.append(f"  rank {r:>3} {'#' * n:<{width}} {lag:>9}us{mark}")
    return lines


def render(state: dict, width: int = 78, color: bool = False,
           last: int = 40) -> List[str]:
    """Pure renderer: /state snapshot -> list of screen lines.

    Kept free of I/O so tests can drive it with a stub state dict.
    """
    lines = []
    steps = state.get("steps") or []
    phases = state.get("phases") or list(PHASE_GLYPHS)
    lines.append(
        f"hvd_top — world {state.get('world', '?')}  "
        f"generation {state.get('elastic_generation', 0)}  "
        f"steps seen {len(steps)}")
    lines.append("")
    shown = steps[-last:]
    if shown:
        times = [sum(s.get("phase_us") or []) for s in shown]
        lines.append(f"step time ({shown[0].get('step')}"
                     f"..{shown[-1].get('step')}):  "
                     f"last {times[-1]}us  max {max(times)}us")
        lines.append("  " + sparkline(times))
        totals: Dict[str, int] = {p: 0 for p in phases}
        for s in shown:
            for i, us in enumerate(s.get("phase_us") or []):
                if i < len(phases):
                    totals[phases[i]] += us
        lines.append("")
        lines.append("phase breakdown "
                     "(N=negotiation-wait F=fusion R=ring B=fence I=idle):")
        lines.append("  " + stacked_bar(totals, min(width - 4, 60), color))
        latest = shown[-1]
        # Data-plane tag per step (cockpit normalizes the numeric tag;
        # "?" covers old payloads and steps traced before any optimizer
        # noted a plane).
        planes = {s.get("plane", "?") for s in shown}
        plane = planes.pop() if len(planes) == 1 else "mixed"
        lines.append(
            f"  dominant: {latest.get('dominant_phase', '?')}"
            f" on rank {latest.get('dominant_rank', -1)}"
            f"  (step {latest.get('step')},"
            f" {latest.get('reported', 0)} ranks reported,"
            f" plane {plane})")
        lines.append("")
        lines.append("per-rank announce lag (latest step):")
        lines.extend(skew_lines(latest.get("lag_us") or []))
    else:
        # Degraded panel: the snapshot has no step-trace fields (plane off,
        # older runtime, or no step completed yet).  Dim, never crash.
        lines.append(_dim("step trace unavailable "
                          "(is HOROVOD_STEP_TRACE on and the job stepping?)",
                          color))
    tenants = state.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append(f"{'tenant':>8}  {'responses':>10}  {'tensors':>9}  "
                     f"{'bytes':>12}")
        for psid in sorted(tenants, key=str):
            t = tenants[psid] or {}
            lines.append(f"{psid:>8}  {t.get('responses', 0):>10}  "
                         f"{t.get('tensors', 0):>9}  "
                         f"{t.get('bytes', 0):>12}")
    mig = state.get("migration") or {}
    if any(mig.values()):
        lines.append("")
        lines.append("migration: "
                     f"{mig.get('migrate_events_total', 0)} events, "
                     f"{mig.get('migrate_bytes_total', 0)} bytes, "
                     f"{mig.get('migrate_fallbacks_total', 0)} fallbacks")
    sr = state.get("straggler_report")
    if sr:
        lines.append("")
        lines.append(f"straggler report: {sr}")
    if "error" in state:
        lines.append(f"state error: {state['error']}")
    return lines


def render_history(history: Optional[dict], width: int = 78,
                   color: bool = False) -> List[str]:
    """Pure renderer: /history (fleethistory-v1) -> long-horizon panel lines.

    A missing endpoint (older runtime), an empty payload (plane off), or a
    malformed one renders a dimmed placeholder — the cockpit keeps working
    against any coordinator generation.
    """
    lines = ["", "fleet history (step p99 / goodput per tier):"]
    tiers = (history or {}).get("tiers") or []
    columns = (history or {}).get("columns") or [
        "ts_us", "step_p99_us", "neg_p99_us", "goodput_ppm",
        "wire_ratio_ppm", "steps"]
    if not isinstance(tiers, list) or not tiers:
        lines.append(_dim("  fleet telemetry unavailable "
                          "(HOROVOD_FLEET_TELEMETRY off or runtime < v11)",
                          color))
        return lines

    def col(row: List, name: str) -> float:
        try:
            return float(row[columns.index(name)])
        except (ValueError, IndexError, TypeError):
            return 0.0

    span = max(10, min(width - 26, 60))
    for tier in tiers:
        period = (tier or {}).get("period_s", "?")
        samples = [(s or []) for s in (tier or {}).get("samples") or []]
        samples = samples[-span:]
        label = f"{period}s"
        if not samples:
            lines.append(_dim(f"  {label:>4} tier: no samples yet", color))
            continue
        p99 = [col(s, "step_p99_us") for s in samples]
        goodput = [col(s, "goodput_ppm") / 1e4 for s in samples]  # -> %
        lines.append(f"  {label:>4} p99     {sparkline(p99)}  "
                     f"last {int(p99[-1])}us")
        lines.append(f"  {label:>4} goodput {sparkline(goodput)}  "
                     f"last {goodput[-1]:.1f}%")
    anomalies = (history or {}).get("anomalies") or []
    if anomalies:
        lines.append("")
        lines.append("sentinel anomalies (newest last):")
        for a in anomalies[-5:]:
            a = a or {}
            lines.append(
                f"  #{a.get('seq', '?')} {a.get('kind', '?')}"
                f" z={float(a.get('score', 0)):.1f}"
                f" value={a.get('value', 0)}"
                f" baseline={a.get('baseline', 0)}"
                f" rank={a.get('rank', -1)}")
    return lines


def follow_events(host: str, port: int) -> int:
    """Tail the /events SSE stream; reconnect across re-formations."""
    url = f"http://{host}:{port}/events"
    while True:
        try:
            with urllib.request.urlopen(url, timeout=None) as resp:
                for raw in resp:
                    line = raw.decode(errors="replace").rstrip("\n")
                    if line.startswith("data: "):
                        print(line[len("data: "):], flush=True)
                    elif line.startswith(":") and "open" in line:
                        print(f"# connected to {url}", file=sys.stderr)
        except KeyboardInterrupt:
            return 0
        except OSError as exc:
            # Re-formation in flight: the driver re-binds the SAME port for
            # the next generation's rank 0, so just retry.
            print(f"# stream dropped ({exc}); reconnecting", file=sys.stderr)
            time.sleep(1.0)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("HOROVOD_COCKPIT_PORT", 0)))
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--last", type=int, default=40,
                   help="steps in the sparkline window")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")
    p.add_argument("--json", action="store_true",
                   help="print the raw /state JSON and exit")
    p.add_argument("--events", action="store_true",
                   help="follow the /events SSE stream instead")
    p.add_argument("--no-color", action="store_true")
    args = p.parse_args(argv)
    if not args.port:
        p.error("--port required (or set HOROVOD_COCKPIT_PORT)")
    if args.events:
        return follow_events(args.host, args.port)
    color = sys.stdout.isatty() and not args.no_color
    try:
        while True:
            state = fetch_json(args.host, args.port, "/state")
            if args.json:
                json.dump(state, sys.stdout, indent=2)
                print()
                return 0
            # /history is best-effort: an older coordinator (404) or a
            # disabled plane must not take the whole dashboard down.
            try:
                history = fetch_json(args.host, args.port, "/history")
            except Exception:  # noqa: BLE001 - degrade to the dimmed panel
                history = {}
            lines = render(state, color=color, last=args.last)
            lines.extend(render_history(history, color=color))
            if not args.once:
                sys.stdout.write("\x1b[H\x1b[2J")  # home + clear
            print("\n".join(lines), flush=True)
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    except OSError as exc:
        print(f"hvd_top: cannot reach http://{args.host}:{args.port} "
              f"({exc}) — is the job running with HOROVOD_COCKPIT=1?",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
