#!/usr/bin/env python
"""Per-step critical-path attribution from step-trace dumps.

Walks the per-rank step-trace dumps (steptrace.<rank>.json, written on
shutdown/abort or saved from ``hvd.step_trace()``), optionally together
with flight-recorder dumps and a merged timeline from
``tools/merge_timeline.py``, and answers the question a timeline makes you
eyeball: *which rank, in which phase, set the pace of each step?*

For every step the tool emits one critical-path row ``(rank, phase,
duration)``:

- the **coordinator's fleet records** are authoritative when present
  (steptrace.0.json): the coordinator has seen every rank's CYCLE-frame
  snapshot for the step plus each rank's announce lag, so its
  ``dominant_rank`` / ``dominant_phase`` attribution already accounts for
  waiting caused by *other* ranks — a straggler shows up as the dominant
  rank even though the waiting happens elsewhere.
- otherwise the row falls back to per-rank dumps: the rank whose step
  wall-clock extent was longest, and that rank's largest phase (excluding
  idle).

The summary reports the **bubble fraction**: the share of traced time the
fleet spent not moving bytes — negotiation-wait + fence + idle over the
total of all phases.  A healthy ring run keeps this low; a straggler or a
too-small fusion buffer pushes it up.

A merged timeline produced by merge_timeline.py (step-trace tracks
included) can stand in for the raw dumps — the "step N" spans and the
phase spans carry the same numbers, re-keyed by pid/args.step — so a
single merged artifact from a crash bundle is enough to run attribution.
Flight-recorder dumps contribute context only: an abort event in one marks
the run aborted in the summary.

Usage:
  python tools/critical_path.py steptrace.*.json [flight.*.json] [merged.json]
  python tools/critical_path.py --json steptrace.0.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# Mirrors kStepPhaseNames in cpp/step_trace.cc; used only when a merged
# timeline (which carries phase names per event) is the sole input and for
# the bubble split below.
PHASES = ["negotiation_wait", "fusion", "ring", "fence", "idle"]

# Phases that are "bubble" (the fleet waiting, not moving bytes) vs "busy".
BUBBLE_PHASES = {"negotiation_wait", "fence", "idle"}

# Flight-recorder event type for abort (kFlightTypesLegend in
# cpp/flight_recorder.cc); used only to flag aborted runs in the summary.
FLIGHT_ABORT_TYPE = 11

# Step-trace plane tag (cpp/step_trace.h: -1 unknown, 0 eager, 1 gspmd),
# carried as the trailing element of step rows and the "plane" key of
# fleet records.  Dumps predating the tag simply lack both — every step
# then attributes to "?".
PLANE_NAMES = {0: "eager", 1: "gspmd"}


def plane_name(tag) -> str:
    return PLANE_NAMES.get(tag, "?")


class RankSteps:
    """Per-rank view: step id -> (start_us, end_us, {phase: us})."""

    def __init__(self, rank: int):
        self.rank = rank
        self.steps: Dict[int, Tuple[int, int, Dict[str, int]]] = {}
        # step id -> plane tag (only for dumps that carry the trailer).
        self.planes: Dict[int, int] = {}


def _load(path: str):
    with open(path) as f:
        return json.load(f)


def classify(doc) -> str:
    if isinstance(doc, dict) and str(doc.get("schema", "")).startswith(
            "steptrace"):
        return "steptrace"
    if isinstance(doc, dict) and "events" in doc:
        return "flight"
    if isinstance(doc, list):
        return "timeline"
    return "unknown"


def ingest_steptrace(doc: dict, ranks: Dict[int, RankSteps],
                     fleet: Dict[int, dict]) -> None:
    rank = doc.get("rank", -1)
    phases = doc.get("phases") or PHASES
    rs = ranks.setdefault(rank, RankSteps(rank))
    for row in doc.get("steps") or []:
        if not (isinstance(row, list) and len(row) >= 3 + len(phases)):
            continue
        sid, start, end = row[0], row[1], row[2]
        rs.steps[sid] = (start, end,
                         {phases[i]: row[3 + i] for i in range(len(phases))})
        if len(row) >= 4 + len(phases):  # trailing plane tag (new dumps)
            rs.planes[sid] = row[3 + len(phases)]
    for f in doc.get("fleet") or []:
        if isinstance(f, dict) and isinstance(f.get("step"), int):
            # Coordinator dumps are authoritative; keep the record with the
            # most ranks reported if two inputs carry the same step.
            prev = fleet.get(f["step"])
            if prev is None or f.get("reported", 0) >= prev.get("reported", 0):
                fleet[f["step"]] = f


def ingest_timeline(events: List[dict], ranks: Dict[int, RankSteps],
                    fleet: Dict[int, dict]) -> None:
    """Reconstruct per-rank step data from merge_timeline.py output.

    The merged timeline re-bases timestamps onto one axis, which is exactly
    what cross-rank attribution wants; pid is the rank.
    """
    for e in events:
        if e.get("ph") != "X" or not isinstance(e.get("args"), dict):
            continue
        sid = e["args"].get("step")
        if not isinstance(sid, int):
            continue
        rank = e.get("pid", -1)
        rs = ranks.setdefault(rank, RankSteps(rank))
        name = e.get("name", "")
        ts, dur = e.get("ts", 0), e.get("dur", 0)
        start, end, phases = rs.steps.get(sid, (ts, ts, {}))
        if name.startswith("step "):
            start, end = ts, ts + dur
        elif name in PHASES:
            phases = dict(phases)
            phases[name] = phases.get(name, 0) + dur
        rs.steps[sid] = (start, end, phases)
    for e in events:
        if (e.get("ph") == "i" and str(e.get("name", "")).startswith(
                "dominant ") and isinstance(e.get("args"), dict)
                and isinstance(e["args"].get("step"), int)):
            sid = e["args"]["step"]
            fleet.setdefault(sid, {
                "step": sid,
                "dominant_phase": e["name"][len("dominant "):],
                "dominant_rank": e["args"].get("rank", -1),
                "reported": 0,
            })


def flight_aborted(doc: dict) -> bool:
    return any(isinstance(r, list) and len(r) >= 3
               and r[2] == FLIGHT_ABORT_TYPE
               for r in doc.get("events") or [])


def critical_rows(ranks: Dict[int, RankSteps],
                  fleet: Dict[int, dict]) -> List[dict]:
    """One attribution row per step id seen anywhere."""
    sids = set(fleet)
    for rs in ranks.values():
        sids.update(rs.steps)
    rows = []
    for sid in sorted(sids):
        # Longest wall-clock extent across ranks — the pace-setter's span.
        wall_rank, wall_us = -1, -1
        for rs in ranks.values():
            if sid in rs.steps:
                start, end, _ = rs.steps[sid]
                if end - start > wall_us:
                    wall_rank, wall_us = rs.rank, end - start
        f = fleet.get(sid)
        if f is not None and f.get("dominant_rank", -1) is not None:
            rank = f.get("dominant_rank", -1)
            phase = f.get("dominant_phase", "?")
            source = "fleet"
        else:
            rank, phase, source = wall_rank, "?", "wall"
            if rank in ranks and sid in ranks[rank].steps:
                phases = ranks[rank].steps[sid][2]
                busy = {p: us for p, us in phases.items() if p != "idle"}
                if busy and max(busy.values()) > 0:
                    phase = max(busy, key=busy.get)
        # Plane attribution: the fleet record's tag when present, else
        # the first per-rank tag seen for the step (dumps without the
        # trailer attribute to "?").
        tag = f.get("plane") if f is not None else None
        if tag is None:
            for rs in ranks.values():
                if sid in rs.planes:
                    tag = rs.planes[sid]
                    if tag in PLANE_NAMES:
                        break
        rows.append({"step": sid, "rank": rank, "phase": phase,
                     "plane": plane_name(tag),
                     "duration_us": max(wall_us, 0), "source": source})
    return rows


def bubble_summary(ranks: Dict[int, RankSteps]) -> dict:
    bubble = busy = 0
    for rs in ranks.values():
        for _, (_, _, phases) in rs.steps.items():
            for p, us in phases.items():
                if p in BUBBLE_PHASES:
                    bubble += us
                else:
                    busy += us
    total = bubble + busy
    return {"bubble_us": bubble, "busy_us": busy,
            "bubble_fraction": (bubble / total) if total else 0.0}


def analyze(paths: List[str]) -> dict:
    ranks: Dict[int, RankSteps] = {}
    fleet: Dict[int, dict] = {}
    aborted = False
    skipped = []
    for p in paths:
        try:
            doc = _load(p)
        except (OSError, json.JSONDecodeError) as e:
            skipped.append(f"{p}: {e}")
            continue
        kind = classify(doc)
        if kind == "steptrace":
            ingest_steptrace(doc, ranks, fleet)
        elif kind == "timeline":
            ingest_timeline(doc, ranks, fleet)
        elif kind == "flight":
            aborted = aborted or flight_aborted(doc)
        else:
            skipped.append(f"{p}: unrecognized format")
    rows = critical_rows(ranks, fleet)
    summary = bubble_summary(ranks)
    summary["steps"] = len(rows)
    summary["ranks"] = sorted(ranks)
    summary["aborted"] = aborted
    # Which (rank, phase) pairs set the pace most often — the headline.
    tally: Dict[Tuple[int, str], int] = {}
    for r in rows:
        key = (r["rank"], r["phase"])
        tally[key] = tally.get(key, 0) + 1
    if tally:
        (rank, phase), n = max(tally.items(), key=lambda kv: kv[1])
        summary["dominant_rank"] = rank
        summary["dominant_phase"] = phase
        summary["dominant_steps"] = n
    # Steps per data plane (the gspmd plane runs no explicit collective,
    # so this is the only offline surface saying which plane set the pace).
    planes: Dict[str, int] = {}
    for r in rows:
        planes[r["plane"]] = planes.get(r["plane"], 0) + 1
    summary["plane_steps"] = planes
    return {"rows": rows, "summary": summary, "skipped": skipped}


def render(result: dict, last: int) -> str:
    rows, summary = result["rows"], result["summary"]
    lines = []
    shown = rows[-last:] if last > 0 else rows
    if len(shown) < len(rows):
        lines.append(f"(showing last {len(shown)} of {len(rows)} steps)")
    lines.append(f"{'step':>6}  {'rank':>4}  {'phase':<18}  {'plane':<6}"
                 f"  {'duration':>10}  src")
    for r in shown:
        lines.append(f"{r['step']:>6}  {r['rank']:>4}  {r['phase']:<18}"
                     f"  {r.get('plane', '?'):<6}"
                     f"  {r['duration_us']:>8}us  {r['source']}")
    lines.append("")
    frac = summary["bubble_fraction"]
    lines.append(f"bubble fraction: {frac:.1%}  "
                 f"(bubble {summary['bubble_us']}us / "
                 f"busy {summary['busy_us']}us, "
                 f"{summary['steps']} steps, ranks {summary['ranks']})")
    if "dominant_rank" in summary:
        lines.append(f"critical path: rank {summary['dominant_rank']} / "
                     f"{summary['dominant_phase']} set the pace on "
                     f"{summary['dominant_steps']}/{summary['steps']} steps")
    planes = summary.get("plane_steps") or {}
    named = {p: n for p, n in planes.items() if p != "?"}
    if named:
        split = ", ".join(f"{p}: {n}" for p, n in sorted(named.items()))
        lines.append(f"data plane: {split}"
                     + (f" (untagged: {planes['?']})" if "?" in planes
                        else ""))
    if summary["aborted"]:
        lines.append("note: a flight-recorder dump records an ABORT — the "
                     "last steps may be partial")
    for s in result["skipped"]:
        lines.append(f"skipped {s}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("inputs", nargs="+",
                   help="steptrace.*.json / flight.*.json / merged timeline")
    p.add_argument("--json", action="store_true",
                   help="emit the full analysis as JSON")
    p.add_argument("--last", type=int, default=20,
                   help="show only the last N steps in the table (0 = all)")
    args = p.parse_args(argv)
    result = analyze(args.inputs)
    if args.json:
        json.dump(result, sys.stdout, indent=2)
        print()
    else:
        print(render(result, args.last))
    return 0 if result["rows"] or not result["skipped"] else 1


if __name__ == "__main__":
    sys.exit(main())
