#!/usr/bin/env python
"""Render a Horovod-TPU crash bundle into a human-readable forensics report.

Input is the directory named by HOROVOD_POSTMORTEM_DIR (or a path straight
to its postmortem.json).  The bundle holds:

  postmortem.json   the coordinator's merged view, written at abort time:
                    culprit rank/host, abort reason, per-rank last-N-event
                    digests collected over the control tree, last-seen
                    negotiation cycles, and which ranks never reported
  flight.<rank>.json  each rank's full flight-recorder ring, dumped locally
                    on abort / fatal signal / injected death — including
                    the culprit's, whose digest could not be collected
                    (it was already dead)
  autopilot.jsonl   the fleet autopilot's decision log (one JSON line per
                    eviction / scale-up / re-admission, written by the
                    elastic driver's policy thread; docs/elastic.md), plus
                    "migrate" rows appended by zero-downtime elastic state
                    migration — rendered so the report shows why the fleet
                    changed shape, not just that it did

The report names the culprit, shows each rank's last-seen state, and prints
the merged causal event sequence leading into the abort.  --trace also
emits a Perfetto-loadable trace via tools/merge_timeline.py so the bundle
can be read on one time axis next to any surviving ranks' timelines.

Usage:
    python tools/postmortem.py /path/to/postmortem-dir
    python tools/postmortem.py bundle/postmortem.json --events 80
    python tools/postmortem.py bundle/ --trace merged.json
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import re
import sys
from typing import Dict, List, Optional


def _load_merge_timeline():
    spec = importlib.util.spec_from_file_location(
        "merge_timeline",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "merge_timeline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def find_bundle(path: str) -> Dict[str, object]:
    """Locate postmortem.json, flight.<rank>.json dumps, and the
    autopilot decision log.

    Returns {"postmortem": path-or-None, "flights": {rank: path},
    "autopilot": path-or-None}.
    """
    if os.path.isdir(path):
        directory = path
        pm = os.path.join(path, "postmortem.json")
    else:
        directory = os.path.dirname(path) or "."
        pm = path
    flights: Dict[int, str] = {}
    for f in sorted(glob.glob(os.path.join(directory, "flight.*.json"))):
        m = re.match(r"flight\.(\d+)\.json$", os.path.basename(f))
        if m:
            flights[int(m.group(1))] = f
    ap = os.path.join(directory, "autopilot.jsonl")
    return {"postmortem": pm if os.path.exists(pm) else None,
            "flights": flights,
            "autopilot": ap if os.path.exists(ap) else None}


# Mirrors cpp/metrics.h MigratePhase (flight type-14 `a` upper byte).
_MIGRATE_PHASES = {1: "replicate", 2: "manifest", 3: "transfer",
                   4: "reassemble", 5: "fallback"}

# Mirrors cpp/fleet_telemetry.cc SentinelKind (flight type-15 `a` upper
# byte); the low byte is dominant_rank+1 (0 = no rank attribution).
_SENTINEL_KINDS = {1: "step_p99", 2: "goodput", 3: "wire_ratio"}

# The flight-recorder event-type table: the Python-side mirror of
# cpp/flight_recorder.h FlightType and flight_recorder.cc
# kFlightTypesLegend.  Dumps carry their own legend (the "types" object),
# which wins when present — this table is the fallback for digests and
# hand-built bundles that lost it.  tools/hvd_lint.py checks all four
# copies (enum, C legend, this table, the docs/observability.md table)
# stay identical, so add new types in all four places.
FLIGHT_TYPES = {
    1: "ctrl_send", 2: "ctrl_recv", 3: "rendezvous", 4: "verdict",
    5: "ring_hop", 6: "wire_codec", 7: "shm_fence", 8: "shm_map",
    9: "tree_aggregate", 10: "fault_trip", 11: "abort", 12: "digest",
    13: "autopilot", 14: "migrate", 15: "sentinel", 16: "hloinspect",
}


def _type_name(typ: int, types: Dict[str, str]) -> str:
    return types.get(str(typ)) or FLIGHT_TYPES.get(typ) or f"type{typ}"


def _fmt_event(row: List[int], types: Dict[str, str],
               abort_us: Optional[int]) -> str:
    ts_us, seq, typ, tid, a, b = row[:6]
    name = _type_name(typ, types)
    rel = "" if abort_us is None else f"{(ts_us - abort_us) / 1e3:+10.1f}ms "
    if name == "migrate":
        # a = phase<<8 | source_rank+1 (0 = no source); b = payload bytes.
        phase = _MIGRATE_PHASES.get(a >> 8, f"phase{a >> 8}")
        src = (a & 0xFF) - 1
        src_s = str(src) if src >= 0 else "-"
        return (f"{rel}seq={seq:<8} {name:<14} tid={tid} "
                f"phase={phase} src={src_s} bytes={b}")
    if name == "sentinel":
        # a = kind<<8 | dominant_rank+1 (0 = no attribution); b = the
        # observed value (us for step_p99, ppm for goodput/wire_ratio).
        kind = _SENTINEL_KINDS.get(a >> 8, f"kind{a >> 8}")
        rank = (a & 0xFF) - 1
        rank_s = str(rank) if rank >= 0 else "-"
        return (f"{rel}seq={seq:<8} {name:<14} tid={tid} "
                f"kind={kind} rank={rank_s} value={b}")
    if name == "hloinspect":
        # a = compiler-inserted collective op count for the inspected
        # gspmd trace; b = its analytic wire bytes (ops/hlo_inspect.py).
        return (f"{rel}seq={seq:<8} {name:<14} tid={tid} "
                f"ops={a} wire_bytes={b}")
    return f"{rel}seq={seq:<8} {name:<14} tid={tid} a={a} b={b}"


def _load_autopilot(path: Optional[str]) -> List[dict]:
    """Parse autopilot.jsonl; malformed lines are skipped, not fatal."""
    if not path:
        return []
    decisions: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(row, dict):
                    decisions.append(row)
    except OSError:
        return []
    return decisions


# Mirrors runner/autopilot.py ACT_* (and the flight type-13 `a` field).
_AUTOPILOT_ACTIONS = {1: "evict", 2: "scale_up", 3: "readmit"}


def report(bundle: Dict[str, object], n_events: int,
           out=sys.stdout) -> int:
    pm_path = bundle["postmortem"]
    flights: Dict[int, str] = bundle["flights"]  # type: ignore[assignment]
    autopilot = _load_autopilot(bundle.get("autopilot"))  # type: ignore[arg-type]
    if pm_path is None and not flights and not autopilot:
        print("error: no postmortem.json, flight.*.json, or "
              "autopilot.jsonl found", file=sys.stderr)
        return 1

    pm = {}
    if pm_path is not None:
        with open(pm_path) as f:
            pm = json.load(f)

    types: Dict[str, str] = pm.get("types") or {}
    ranks: Dict[str, dict] = dict(pm.get("ranks") or {})
    culprit = pm.get("culprit_rank", -1)

    # Fold in full local dumps: they supersede a 128-event digest and are
    # the only record of the culprit (dead before digest collection).
    for rank, path in flights.items():
        try:
            with open(path) as f:
                dump = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        types = types or dump.get("types") or {}
        rec = ranks.setdefault(str(rank), {})
        rec["source"] = (rec.get("source", "") + "+dump").lstrip("+")
        rec.setdefault("host", dump.get("host", ""))
        rec["events"] = dump.get("events") or rec.get("events") or []
        rec["dropped"] = dump.get("dropped", 0)

    print("=" * 72, file=out)
    print("Horovod-TPU post-mortem", file=out)
    print("=" * 72, file=out)
    if pm:
        print(f"schema          : {pm.get('schema', '?')} "
              f"(protocol v{pm.get('protocol_version', '?')})", file=out)
        print(f"world size      : {pm.get('world_size', '?')}", file=out)
        print(f"culprit         : rank {culprit} "
              f"on {pm.get('culprit_host') or '?'}", file=out)
        print(f"reason          : {pm.get('reason', '?')}", file=out)
    missing = set(pm.get("missing_ranks") or [])
    cycles = pm.get("last_seen_cycles") or {}

    print("\nPer-rank state", file=out)
    print("-" * 72, file=out)
    all_ranks = sorted({int(r) for r in ranks} | missing | {
        int(r) for r in cycles})
    for rank in all_ranks:
        rec = ranks.get(str(rank), {})
        mark = " <- culprit" if rank == culprit else ""
        if not rec and rank in missing:
            print(f"  rank {rank:<3} MISSING (no digest, no dump; last "
                  f"cycle {cycles.get(str(rank), '?')}){mark}", file=out)
            continue
        evs = rec.get("events") or []
        last = (_fmt_event(evs[-1], types, None).strip() if evs
                else "no events")
        print(f"  rank {rank:<3} source={rec.get('source', '?'):<12} "
              f"host={rec.get('host') or '?':<12} "
              f"cycle={cycles.get(str(rank), '?'):<6} "
              f"events={len(evs):<4} last: {last}{mark}", file=out)

    # Causal sequence: everything merged on the wall clock, tail-first cut.
    merged = []
    for rank_str, rec in ranks.items():
        for row in rec.get("events") or []:
            if isinstance(row, list) and len(row) >= 6:
                merged.append((row[0], int(rank_str), row))
    merged.sort(key=lambda t: (t[0], t[2][1]))
    abort_us = None
    for ts_us, _, row in merged:
        if _type_name(row[2], types) == "abort":
            abort_us = ts_us
            break
    tail = merged[-n_events:]
    print(f"\nCausal event sequence (last {len(tail)} of {len(merged)}, "
          "relative to first abort observation)", file=out)
    print("-" * 72, file=out)
    for ts_us, rank, row in tail:
        print(f"  rank {rank:<3} {_fmt_event(row, types, abort_us)}",
              file=out)
    if autopilot:
        print(f"\nAutopilot decisions ({len(autopilot)})", file=out)
        print("-" * 72, file=out)
        for d in autopilot:
            action = d.get("action")
            if isinstance(action, str):
                # Newer rows (elastic migration) journal the action name
                # directly instead of an ACT_* code.
                name = action
            else:
                name = _AUTOPILOT_ACTIONS.get(action, f"action{action}")
            ts = d.get("ts")
            ts_s = f"t={ts:10.3f}s " if isinstance(ts, (int, float)) else ""
            print(f"  {ts_s}gen={d.get('generation', '?'):<3} "
                  f"{name:<9} rank={d.get('rank', '?'):<3} "
                  f"{d.get('detail', '')}", file=out)

    if pm:
        print(f"\nmissing ranks   : {sorted(missing) or 'none'}", file=out)
    return 0


def write_trace(bundle: Dict[str, object], out_path: str) -> None:
    """Emit a Perfetto trace through merge_timeline's flight ingestion.

    Each rank record is re-shaped into a flight-dump object (the format
    merge_timeline.load_trace detects) so digests and full dumps ride the
    same alignment path as timeline files.
    """
    import tempfile

    mt = _load_merge_timeline()
    pm_path = bundle["postmortem"]
    flights: Dict[int, str] = bundle["flights"]  # type: ignore[assignment]
    paths: List[str] = []
    tmpdir = tempfile.mkdtemp(prefix="hvd_postmortem_")
    if pm_path is not None:
        with open(pm_path) as f:
            pm = json.load(f)
        for rank_str, rec in (pm.get("ranks") or {}).items():
            if int(rank_str) in flights:
                continue  # the full dump supersedes the digest
            dump = {"rank": int(rank_str), "host": rec.get("host", ""),
                    "types": pm.get("types")
                    or {str(k): v for k, v in FLIGHT_TYPES.items()},
                    "events": rec.get("events") or []}
            p = os.path.join(tmpdir, f"digest.{rank_str}.json")
            with open(p, "w") as f:
                json.dump(dump, f)
            paths.append(p)
    paths.extend(flights[r] for r in sorted(flights))
    if not paths:
        print("no events to trace", file=sys.stderr)
        return
    merged = mt.merge(paths)
    with open(out_path, "w") as f:
        json.dump(merged, f)
    print(f"wrote {out_path}: {len(merged)} events", file=sys.stderr)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("bundle", help="postmortem directory or postmortem.json")
    p.add_argument("--events", type=int, default=40,
                   help="causal-sequence tail length (default 40)")
    p.add_argument("--trace", default=None, metavar="OUT.json",
                   help="also write a Perfetto-loadable merged trace")
    args = p.parse_args(argv)
    bundle = find_bundle(args.bundle)
    rc = report(bundle, args.events)
    if rc == 0 and args.trace:
        write_trace(bundle, args.trace)
    return rc


if __name__ == "__main__":
    sys.exit(main())
