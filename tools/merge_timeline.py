#!/usr/bin/env python
"""Merge per-rank Horovod-TPU timeline traces into one Perfetto-loadable
trace.

Each rank writes its own Chrome-trace JSON array (HOROVOD_TIMELINE or
hvd.start_timeline) with ts relative to that rank's own Start() and pid 0.
This tool puts all ranks on one time axis and one trace:

- clock alignment: every rank emits a RENDEZVOUS instant immediately after
  the synchronized controller handshake in hvd.init(), so those instants
  happened at (nearly) the same wall-clock moment on every rank.  All
  timestamps are shifted so the RENDEZVOUS events coincide with the
  reference rank's (the first input file's).  Traces started manually after
  init have no RENDEZVOUS; then the CLOCK_SYNC anchor's wall-clock reading
  (args.unix_us, taken at trace ts 0) aligns them instead — good on one
  host, NTP-grade across hosts.  With neither anchor, timestamps pass
  through unshifted.
- identity: pid is rewritten to the rank (parsed from CLOCK_SYNC args.rank,
  else the input-file order), and process_name / process_sort_index
  metadata events make Perfetto label and order the tracks "rank N".
- robustness: a trace cut off mid-write (rank crashed before Stop closed
  the array) is repaired by trimming to the last complete event.
- flight-recorder dumps: an input that is a flight-recorder JSON object
  (flight.<rank>.json crash bundles, or hvd.flight_record() saved to disk)
  rather than a Chrome-trace array is converted into instants on its own
  rank track, with the event-type legend resolved to names and a CLOCK_SYNC
  anchor synthesized from the first event's wall-clock timestamp — so a
  crash bundle merges onto the same axis as surviving ranks' timelines.
- the ABORT instant (emitted with culprit metadata in args) is promoted to
  a global-scope instant so Perfetto draws it across every track.
- step-trace dumps: an input that is a step-trace JSON object
  (steptrace.<rank>.json, or hvd.step_trace() saved to disk) becomes a
  per-rank "step phases" track — one complete event per step plus the
  phase breakdown laid out in phase order inside it — and, for the
  coordinator's dump, stacked "fleet phase us" counter tracks with a
  "dominant <phase>" instant per step carrying the attributed rank.

Usage:  python tools/merge_timeline.py rank*.json flight.*.json \
            steptrace.*.json -o merged.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple


def flight_to_events(dump: dict) -> List[dict]:
    """Convert a flight-recorder dump into Chrome-trace instants.

    Rows are [ts_us, seq, type, tid, a, b] with ts_us in wall-clock
    microseconds; re-basing on the first event's ts and carrying it as a
    CLOCK_SYNC anchor reuses the existing wall-clock alignment path, so the
    dump lands on the merged axis without a RENDEZVOUS instant.
    """
    rank = dump.get("rank", -1)
    types = dump.get("types") or {}
    rows = [r for r in dump.get("events") or []
            if isinstance(r, list) and len(r) >= 6]
    if not rows:
        return []
    t0 = rows[0][0]
    out = [{"name": "CLOCK_SYNC", "ph": "i", "ts": 0, "pid": 0, "tid": 0,
            "s": "p", "args": {"rank": rank, "unix_us": t0, "flight": True}}]
    for ts_us, seq, typ, tid, a, b in rows:
        out.append({"name": types.get(str(typ), f"flight:{typ}"),
                    "ph": "i", "ts": ts_us - t0, "pid": 0, "tid": tid,
                    "s": "t", "args": {"seq": seq, "a": a, "b": b}})
    return out


# Synthetic thread ids for step-trace tracks, far above any real OS tid the
# timeline writer records, so the tracks never collide with genuine threads
# when a rank's timeline and its step-trace dump are merged together.
STEP_TID = 900_000
PHASE_TID = 900_001
DOMINANT_TID = 900_002


def steptrace_to_events(dump: dict) -> List[dict]:
    """Convert a step-trace dump into a per-rank "step phases" track.

    Step rows are [step, start_us, end_us, <phase us...>] with wall-clock
    microsecond bounds; phases have only per-step sums (no individual
    timestamps), so they are laid out back-to-back from the step's start in
    the dump's declared phase order — the stack shows *proportion*, the
    enclosing "step N" span shows true wall-clock extent.  Fleet records
    (coordinator dump only) become a stacked counter track plus one
    "dominant <phase>" instant per step with the attributed rank in args.
    """
    rank = dump.get("rank", -1)
    phases = dump.get("phases") or []
    rows = [r for r in dump.get("steps") or []
            if isinstance(r, list) and len(r) >= 3 + len(phases)]
    if not rows:
        return []
    rows.sort(key=lambda r: r[1])
    t0 = rows[0][1]
    out = [{"name": "CLOCK_SYNC", "ph": "i", "ts": 0, "pid": 0, "tid": 0,
            "s": "p", "args": {"rank": rank, "unix_us": t0,
                               "steptrace": True}},
           {"name": "thread_name", "ph": "M", "pid": 0, "tid": STEP_TID,
            "args": {"name": "steps"}},
           {"name": "thread_name", "ph": "M", "pid": 0, "tid": PHASE_TID,
            "args": {"name": "step phases"}}]
    end_by_step = {}
    for row in rows:
        sid, start, end = row[0], row[1], row[2]
        end_by_step[sid] = end
        out.append({"name": f"step {sid}", "ph": "X", "ts": start - t0,
                    "dur": max(end - start, 1), "pid": 0, "tid": STEP_TID,
                    "args": {"step": sid}})
        cursor = start
        for i, pname in enumerate(phases):
            us = row[3 + i]
            if us > 0:
                out.append({"name": pname, "ph": "X", "ts": cursor - t0,
                            "dur": us, "pid": 0, "tid": PHASE_TID,
                            "args": {"step": sid}})
                cursor += us
    fleet = [f for f in dump.get("fleet") or []
             if isinstance(f, dict) and f.get("step") in end_by_step]
    if fleet:
        out.append({"name": "thread_name", "ph": "M", "pid": 0,
                    "tid": DOMINANT_TID, "args": {"name": "dominant"}})
    for f in fleet:
        ts = end_by_step[f["step"]] - t0
        counts = {phases[i]: v for i, v in enumerate(f.get("phase_us") or [])
                  if i < len(phases)}
        out.append({"name": "fleet phase us", "ph": "C", "ts": ts,
                    "pid": 0, "tid": 0, "args": counts})
        out.append({"name": f"dominant {f.get('dominant_phase', '?')}",
                    "ph": "i", "ts": ts, "pid": 0, "tid": DOMINANT_TID,
                    "s": "t", "args": {"step": f["step"],
                                       "rank": f.get("dominant_rank", -1)}})
    return out


def load_trace(path: str) -> List[dict]:
    """Load one per-rank trace, repairing a truncated (crashed-rank) file.

    A flight-recorder dump (JSON object with an "events" array of compact
    rows) or a step-trace dump (schema "steptrace-v1") is accepted too and
    converted into events on its rank's track.
    """
    with open(path) as f:
        text = f.read()
    try:
        events = json.loads(text)
    except json.JSONDecodeError:
        # The writer appends ",\n{event}" and only Stop() writes the closing
        # "]"; trim back to the last complete event and close the array.
        body = text.strip()
        if body.startswith("["):
            body = body[1:]
        cut = body.rfind("}")
        events = json.loads("[" + body[: cut + 1] + "]") if cut >= 0 else []
    if isinstance(events, dict) and str(
            events.get("schema", "")).startswith("steptrace"):
        return steptrace_to_events(events)
    if isinstance(events, dict) and "events" in events:
        return flight_to_events(events)
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome-trace JSON array")
    return [e for e in events if isinstance(e, dict)]


def rank_of(events: List[dict], fallback: int) -> int:
    for e in events:
        if e.get("name") == "CLOCK_SYNC":
            r = (e.get("args") or {}).get("rank", -1)
            if isinstance(r, int) and r >= 0:
                return r
    return fallback


def anchors(events: List[dict]) -> Tuple[Optional[int], Optional[int]]:
    """(rendezvous_ts, clock_sync_unix_us) — either may be absent."""
    rendezvous = None
    unix_us = None
    for e in events:
        if e.get("name") == "RENDEZVOUS" and rendezvous is None:
            rendezvous = e.get("ts")
        elif e.get("name") == "CLOCK_SYNC" and unix_us is None:
            unix_us = (e.get("args") or {}).get("unix_us")
    return rendezvous, unix_us


def merge(paths: List[str]) -> List[dict]:
    traces = [load_trace(p) for p in paths]
    ranks = [rank_of(t, i) for i, t in enumerate(traces)]
    anchor = [anchors(t) for t in traces]
    ref_rdv, ref_unix = anchor[0]

    merged: List[dict] = []
    for rank in sorted(set(ranks)):
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        merged.append({"name": "process_sort_index", "ph": "M", "pid": rank,
                       "args": {"sort_index": rank}})
    for trace, rank, (rdv, unix_us) in zip(traces, ranks, anchor):
        if rdv is not None and ref_rdv is not None:
            shift = ref_rdv - rdv
        elif unix_us is not None and ref_unix is not None:
            # ts is relative to this rank's t0; its wall clock at t0 was
            # unix_us.  Shifting by the wall-clock skew of the t0s puts all
            # ranks on the reference rank's axis.
            shift = unix_us - ref_unix
        else:
            shift = 0
        for e in trace:
            out = dict(e)
            out["pid"] = rank
            if isinstance(out.get("ts"), (int, float)):
                out["ts"] = out["ts"] + shift
            if out.get("name") == "ABORT":
                # Draw the abort (with its culprit args) across all tracks.
                out["s"] = "g"
            merged.append(out)
    # Stable sort keeps each rank's B-before-E ordering at equal ts.
    merged.sort(key=lambda e: (e.get("ph") != "M",
                               e.get("ts", 0) if e.get("ph") != "M" else 0))
    return merged


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("inputs", nargs="+", help="per-rank timeline JSON files")
    p.add_argument("-o", "--output", default="merged_timeline.json")
    args = p.parse_args(argv)
    merged = merge(args.inputs)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    n_ranks = len({e["pid"] for e in merged})
    print(f"wrote {args.output}: {len(merged)} events from {n_ranks} ranks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
