"""On-chip validation of the Pallas flash-attention kernels (VERDICT r4 #3c).

Runs on the real TPU (not interpret mode) and checks, in order:

1. Forward numerics: flash vs dense, f32 + bf16, causal + full, including a
   ragged sequence length (padding path).
2. Backward numerics: grads of a scalar loss through the custom_vjp
   (dq/dk/dv) vs grads through the dense reference.
3. The lse-pair VJP used by ring attention (cotangent on lse folds into
   delta) vs an autodiff-through-dense-with-lse reference.
4. The compiled pallas-inside-switch-inside-fori_loop composition that
   ring_attention(use_flash=True) builds: run it under shard_map on a
   1-device mesh (real hardware compile + execute), and additionally
   validate multi-hop merge math by chunking K/V on one chip.
5. Performance: flash vs dense (XLA) fwd and fwd+bwd wall time across
   sequence lengths, bf16.  The use_flash default flip is gated on this.

Prints one JSON line per section and a final summary line starting with
"RESULT ".  Exit code 0 iff every numeric check passed.
"""

from __future__ import annotations

import functools
import json
import sys
import time

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.ops.flash_attention import (  # noqa: E402
    dense_attention, dense_attention_with_lse,
    flash_attention, flash_attention_with_lse)
from horovod_tpu.parallel.ring_attention import ring_attention  # noqa: E402

RESULTS = {}
FAILED = []


def log(section, **kv):
    RESULTS[section] = kv
    print(json.dumps({"section": section, **kv}), flush=True)


def err(name, a, b, tol):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    e = float(np.max(np.abs(a - b)))
    rel = e / max(1e-12, float(np.max(np.abs(b))))
    ok = rel < tol
    if not ok:
        FAILED.append(f"{name}: rel={rel:.3e} tol={tol:.1e}")
    return {"name": name, "max_abs": e, "max_rel": rel, "ok": ok}


def mk(b, s, h, d, dtype, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def fwd_numerics():
    # f32 tolerance is MXU-default-precision-calibrated: on TPU, f32 dots
    # run as bf16 passes by default (both in the kernel and in the dense
    # reference), so rel ~2e-3 is expected, not a kernel bug.
    checks = []
    for dtype, tol in ((jnp.float32, 6e-3), (jnp.bfloat16, 2e-2)):
        for causal in (False, True):
            for s in (512, 777):  # 777 exercises the padding path
                q, k, v = mk(2, s, 4, 64, dtype)
                ref = dense_attention(q.astype(jnp.float32),
                                      k.astype(jnp.float32),
                                      v.astype(jnp.float32), causal)
                out = flash_attention(q, k, v, causal)
                out = jax.block_until_ready(out)
                checks.append(err(
                    f"fwd/{jnp.dtype(dtype).name}/causal={causal}/s={s}",
                    out, ref, tol))
    log("fwd_numerics", checks=checks)


def bwd_numerics():
    checks = []
    for dtype, tol in ((jnp.float32, 6e-3), (jnp.bfloat16, 4e-2)):
        for causal in (False, True):
            q, k, v = mk(2, 512, 4, 64, dtype, key=1)
            w = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)

            def loss(fn, q, k, v):
                return jnp.sum(fn(q, k, v, causal).astype(jnp.float32) * w)

            gf = jax.grad(functools.partial(loss, flash_attention),
                          argnums=(0, 1, 2))(q, k, v)
            gd = jax.grad(functools.partial(loss, dense_attention),
                          argnums=(0, 1, 2))(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32))
            gf = jax.block_until_ready(gf)
            for name, a, b in zip("dq dk dv".split(), gf, gd):
                checks.append(err(
                    f"bwd/{jnp.dtype(dtype).name}/causal={causal}/{name}",
                    a, b, tol))
    log("bwd_numerics", checks=checks)


def lse_pair_vjp():
    # Ring attention differentiates through (out, lse); the dlse cotangent
    # folds into delta.  Compare against autodiff through the dense pair.
    checks = []
    q, k, v = mk(2, 256, 4, 64, jnp.float32, key=2)
    wo = jax.random.normal(jax.random.PRNGKey(3), q.shape, jnp.float32)
    wl = jax.random.normal(jax.random.PRNGKey(4), (2, 4, 256), jnp.float32)

    def loss(fn, q, k, v):
        out, lse = fn(q, k, v, True)
        return jnp.sum(out.astype(jnp.float32) * wo) + jnp.sum(lse * wl)

    gf = jax.grad(functools.partial(loss, flash_attention_with_lse),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(functools.partial(loss, dense_attention_with_lse),
                  argnums=(0, 1, 2))(q, k, v)
    gf = jax.block_until_ready(gf)
    for name, a, b in zip("dq dk dv".split(), gf, gd):
        checks.append(err(f"lse_vjp/{name}", a, b, 6e-3))
    log("lse_pair_vjp", checks=checks)


def ring_composition():
    # (a) The exact use_flash composition under shard_map on a 1-device
    # mesh: real-hardware compile + run of pallas inside lax.switch inside
    # fori_loop inside shard_map.
    checks = []
    q, k, v = mk(2, 512, 4, 64, jnp.bfloat16, key=5)
    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    for causal in (False, True):
        fn = shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=causal,
                              use_flash=True, block_size=128),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_rep=False)
        out = jax.block_until_ready(jax.jit(fn)(q, k, v))
        ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), causal)
        checks.append(err(f"ring1dev/causal={causal}", out, ref, 2e-2))

    # (b) Multi-hop merge math on one chip: chunk K/V into 4 hops and run
    # the same per-hop kernel + online merge the ring performs, vs dense.
    n = 4
    q, k, v = mk(2, 1024, 4, 64, jnp.bfloat16, key=6)
    sl = 1024 // n
    for causal in (False, True):
        # simulate rank r = n-1 (sees all chunks) for causal; any rank for
        # full attention.
        r = n - 1
        qs = q[:, r * sl:(r + 1) * sl]
        acc = jnp.zeros(qs.shape, jnp.float32)
        m = jnp.full((2, 4, sl), -jnp.inf, jnp.float32)
        l = jnp.zeros((2, 4, sl), jnp.float32)
        for src in range(n):
            kc = k[:, src * sl:(src + 1) * sl]
            vc = v[:, src * sl:(src + 1) * sl]
            if causal and src == r:
                out, lse = flash_attention_with_lse(qs, kc, vc, causal=True)
            elif causal and src > r:
                continue
            else:
                out, lse = flash_attention_with_lse(qs, kc, vc, causal=False)
            ctx, m_c, l_c = out.astype(jnp.float32), lse, lse * 0 + 1.0
            m_new = jnp.maximum(m, m_c)
            alpha = jnp.nan_to_num(
                jnp.exp(jnp.where(m == -jnp.inf, -jnp.inf, m - m_new)))
            beta = jnp.nan_to_num(
                jnp.exp(jnp.where(m_c == -jnp.inf, -jnp.inf, m_c - m_new)))
            l = l * alpha + l_c * beta
            bh = lambda x: jnp.transpose(x, (0, 2, 1))[..., None]
            acc = acc * bh(alpha) + ctx * bh(beta)
            m = m_new
        got = acc / jnp.transpose(jnp.maximum(l, 1e-30), (0, 2, 1))[..., None]
        ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), causal)[:, r * sl:(r + 1) * sl]
        checks.append(err(f"ring_merge4/causal={causal}",
                          jax.block_until_ready(got), ref, 2e-2))
    log("ring_composition", checks=checks)


def _time(fn, *args, iters=20, warmup=3):
    """Readback-honest timing: block_until_ready does NOT synchronize over
    this sandbox's remote-TPU tunnel (PERF_LAST_GOOD.json methodology), so
    iterations CHAIN through the first output (q <- out, same shape/dtype)
    and the loop ends with a scalar host readback that bounds every
    enqueued step."""
    args = list(args)

    def chain(out):
        first = out[0] if isinstance(out, (tuple, list)) else out
        if first.shape == args[0].shape and first.dtype == args[0].dtype:
            args[0] = first
        return first

    for _ in range(warmup):
        out = chain(fn(*args))
    float(jnp.sum(out[(0,) * out.ndim]))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = chain(fn(*args))
    float(jnp.sum(out[(0,) * out.ndim]))
    return (time.perf_counter() - t0) / iters


def perf():
    rows = []
    b, h, d = 4, 8, 128
    for s in (1024, 2048, 4096, 8192):
        q, k, v = mk(b, s, h, d, jnp.bfloat16, key=7)
        for causal in (False, True):
            flash_f = jax.jit(functools.partial(flash_attention, causal=causal))
            dense_f = jax.jit(functools.partial(dense_attention, causal=causal))

            def mkloss(fn):
                return jax.jit(jax.grad(
                    lambda q, k, v: jnp.sum(
                        fn(q, k, v, causal).astype(jnp.float32)),
                    argnums=(0, 1, 2)))

            flash_g = mkloss(flash_attention)
            dense_g = mkloss(dense_attention)

            def timed(fn, *a, **kw):
                try:
                    return _time(fn, *a, **kw)
                except Exception as e:  # OOM at long seq: record, keep going
                    print(json.dumps({"section": "perf_skip", "seq": s,
                                      "causal": causal,
                                      "error": str(e)[:200]}), flush=True)
                    return float("nan")

            tf = timed(flash_f, q, k, v)
            td = timed(dense_f, q, k, v)
            tfg = timed(flash_g, q, k, v, iters=10)
            tdg = timed(dense_g, q, k, v, iters=10)
            # attention flops: 2 * 2 * B*H*S^2*D (QK^T and PV), x3.5 for bwd
            fl = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)
            rows.append({
                "seq": s, "causal": causal,
                "flash_fwd_ms": tf * 1e3, "dense_fwd_ms": td * 1e3,
                "flash_fwdbwd_ms": tfg * 1e3, "dense_fwdbwd_ms": tdg * 1e3,
                "fwd_speedup": td / tf, "fwdbwd_speedup": tdg / tfg,
                "flash_fwd_tflops": fl / tf / 1e12,
            })
            print(json.dumps({"section": "perf_row", **rows[-1]}), flush=True)
    log("perf", rows=rows)
    return rows


def sweep():
    """Block-size sweep at seq 4096, bf16 — picks the kernel defaults."""
    rows = []
    b, h, d = 4, 8, 128
    s = 4096
    q, k, v = mk(b, s, h, d, jnp.bfloat16, key=8)
    for bq in (128, 256, 512, 1024):
        for bk in (128, 256, 512, 1024):
            for causal in (False, True):
                if causal and bq != bk:
                    continue
                try:
                    f = jax.jit(functools.partial(
                        flash_attention, causal=causal, block_q=bq,
                        block_k=bk))
                    g = jax.jit(jax.grad(
                        lambda q, k, v: jnp.sum(flash_attention(
                            q, k, v, causal, block_q=bq,
                            block_k=bk).astype(jnp.float32)),
                        argnums=(0, 1, 2)))
                    tf = _time(f, q, k, v, iters=10)
                    tg = _time(g, q, k, v, iters=5)
                except Exception as e:
                    print(json.dumps({"section": "sweep_skip", "bq": bq,
                                      "bk": bk, "causal": causal,
                                      "error": str(e)[:160]}), flush=True)
                    continue
                fl = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)
                rows.append({"bq": bq, "bk": bk, "causal": causal,
                             "fwd_ms": tf * 1e3, "fwdbwd_ms": tg * 1e3,
                             "fwd_tflops": fl / tf / 1e12})
                print(json.dumps({"section": "sweep_row", **rows[-1]}),
                      flush=True)
    log("sweep", rows=rows)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"section": "device", "kind": dev.device_kind,
                      "backend": jax.default_backend()}), flush=True)
    only = sys.argv[1] if len(sys.argv) > 1 else None
    sections = {"fwd": fwd_numerics, "bwd": bwd_numerics,
                "lse": lse_pair_vjp, "ring": ring_composition,
                "sweep": sweep}
    if only and only != "perf" and only not in sections:
        print(f"unknown section {only!r}; valid: "
              f"{', '.join(list(sections) + ['perf'])}", file=sys.stderr)
        return 2
    if only == "sweep":
        sweep()
        print("RESULT " + json.dumps({"sweep_done": True}), flush=True)
        return 0
    if only and only != "perf":
        sections[only]()
        print("RESULT " + json.dumps({"numerics_ok": not FAILED,
                                      "failed": FAILED}), flush=True)
        return 0 if not FAILED else 1
    if not only:
        # The block-size sweep is a standalone tuning mode ("sweep" arg),
        # not part of routine validation — it adds many minutes of
        # hardware compiles and feeds nothing into the RESULT summary.
        for name, fn in sections.items():
            if name != "sweep":
                fn()
    rows = perf()
    import math

    min_speedup = min((r["fwd_speedup"] for r in rows
                       if r["seq"] >= 2048 and not math.isnan(r["fwd_speedup"])),
                      default=float("nan"))
    summary = {
        "numerics_ok": not FAILED,
        "failed": FAILED,
        "min_fwd_speedup_s2k_plus": min_speedup,
        "flip_use_flash_default": (not FAILED) and min_speedup >= 1.0,
    }
    print("RESULT " + json.dumps(summary), flush=True)
    return 0 if not FAILED else 1


if __name__ == "__main__":
    sys.exit(main())
