"""Steady-state negotiation benchmark: response-cache on vs off vs
fusion-off at np=4 (SURVEY.md §5 — "the response-cache bit-vector trick
matters even more on TPU": DCN round-trips are pricier than MPI ones).

Measures, per configuration:
- steady-state cycle throughput (gradient-bucket steps/s, 50 named
  tensors per step, the DistributedOptimizer eager shape), and
- negotiation ctrl-channel bytes per step on a worker rank (cache hits
  travel as 16-byte (id, handle) pairs; misses re-serialize the full
  request metadata every cycle).

Usage: python tools/bench_negotiation.py [--np 4] [--steps 60]
Prints one JSON line per configuration plus a summary ratio line.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _worker(steps: int, tensors: int):
    import time

    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu import mpi_ops
    from horovod_tpu.context import HorovodContext

    hvd.init(build_mesh=False)
    grads = [np.full(64, float(i), np.float32) for i in range(tensors)]

    def step(tag):
        hs = [mpi_ops.allreduce_async(g, name=f"grad.{i}", op=hvd.Sum)
              for i, g in enumerate(grads)]
        for h in hs:
            mpi_ops.synchronize(h)

    # Warmup: populate the response cache / reach steady state.
    for s in range(5):
        step(s)
    core = HorovodContext.instance().core
    stats0 = core.negotiation_stats() if hasattr(core, "negotiation_stats") \
        else None
    t0 = time.perf_counter()
    for s in range(steps):
        step(s)
    dt = time.perf_counter() - t0
    result = {"rank": hvd.rank(), "steps_per_s": steps / dt,
              "tensor_ops_per_s": steps * len(grads) / dt}
    if stats0 is not None:
        stats1 = core.negotiation_stats()
        # Announce direction (worker -> coordinator): where the cache's
        # (id, handle) pairs replace full request metadata.  The recv
        # direction is the response list, identical in both configs.
        result["announce_bytes_per_step"] = (
            (stats1["ctrl_sent"] - stats0["ctrl_sent"]) / steps)
        result["ctrl_bytes_per_step"] = (
            (stats1["ctrl_sent"] + stats1["ctrl_recv"]
             - stats0["ctrl_sent"] - stats0["ctrl_recv"]) / steps)
    hvd.shutdown()
    return result


def run_config(name: str, env: dict, np_: int, steps: int, tensors: int):
    from horovod_tpu.runner import run

    full_env = {"JAX_PLATFORMS": "cpu", **env}
    results = run(_worker, args=(steps, tensors), np=np_, env=full_env,
                  stream_prefix=False)
    agg = {
        "config": name,
        "np": np_,
        "steps_per_s": round(min(r["steps_per_s"] for r in results), 2),
        "tensor_ops_per_s": round(
            min(r["tensor_ops_per_s"] for r in results), 1),
    }
    per_step = [r.get("ctrl_bytes_per_step") for r in results[1:]]
    if per_step and per_step[0] is not None:
        # Worker ranks only: the coordinator's ctrl traffic counts every
        # worker's frames and would double-book.
        agg["worker_ctrl_bytes_per_step"] = round(max(per_step), 1)
        agg["worker_announce_bytes_per_step"] = round(
            max(r["announce_bytes_per_step"] for r in results[1:]), 1)
    print(json.dumps(agg), flush=True)
    return agg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=4)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--tensors", type=int, default=50)
    args = ap.parse_args()
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    cache_on = run_config("cache_on", {}, args.np, args.steps, args.tensors)
    cache_off = run_config("cache_off", {"HOROVOD_CACHE_CAPACITY": "0"},
                           args.np, args.steps, args.tensors)
    fusion_off = run_config(
        "fusion_off", {"HOROVOD_FUSION_THRESHOLD": "1"},
        args.np, args.steps, args.tensors)

    summary = {
        "metric": "negotiation_cache_speedup",
        "steps_ratio_cache_on_vs_off": round(
            cache_on["steps_per_s"] / cache_off["steps_per_s"], 3),
        "steps_ratio_cache_on_vs_fusion_off": round(
            cache_on["steps_per_s"] / fusion_off["steps_per_s"], 3),
    }
    if "worker_ctrl_bytes_per_step" in cache_on and \
            "worker_ctrl_bytes_per_step" in cache_off:
        summary["ctrl_bytes_ratio_on_vs_off"] = round(
            cache_on["worker_ctrl_bytes_per_step"]
            / max(cache_off["worker_ctrl_bytes_per_step"], 1.0), 3)
        summary["announce_bytes_ratio_on_vs_off"] = round(
            cache_on["worker_announce_bytes_per_step"]
            / max(cache_off["worker_announce_bytes_per_step"], 1.0), 3)
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
