"""Steady-state negotiation benchmark: response-cache on vs off vs
fusion-off at np=4 (SURVEY.md §5 — "the response-cache bit-vector trick
matters even more on TPU": DCN round-trips are pricier than MPI ones).

Measures, per configuration:
- steady-state cycle throughput (gradient-bucket steps/s, 50 named
  tensors per step, the DistributedOptimizer eager shape), and
- negotiation ctrl-channel bytes per step on a worker rank (cache hits
  travel as 16-byte (id, handle) pairs; misses re-serialize the full
  request metadata every cycle).

Usage: python tools/bench_negotiation.py [--np 4] [--steps 60]
Prints one JSON line per configuration plus a summary ratio line.

With --wire-compression {bf16,int8} an additional data-plane section runs:
a large fp32 allreduce over two fake hosts with the hierarchical plane (so
the codec engages on the cross-host leader ring), reporting cross-host
wire bytes/step against the fp32 baseline and the max abs error the codec
introduced.

With --device-codec {int8,int4,int8g} an additional device-plane section
runs: a jitted shard_map allreduce over a forced 8-device CPU host
platform with the HOROVOD_WIRE_COMPRESSION ``device=`` plane on vs off,
reporting the codec's encoded-vs-raw wire ratio (from the device-plane
byte counters), the quantization error, and throughput against the
uncompressed traced ring.  --device-schedule {auto,ring,bidi,torus}
selects the ring topology (HOROVOD_DEVICE_SCHEDULE); pass it alone or
with --device-codec to sweep schedules at a fixed codec.  On CPU the
ratio is the point — the hop count is what the schedules change, and
interpret-mode kernels are not a speed story.

With --data-plane an additional section times one SGD train step under
the eager plane (shard_map + the optimizer's explicit psum) vs the gspmd
plane (batch-sharded inputs + compiler-inserted collectives) on the
forced 8-device CPU mesh — interleaved, best-of-3 per plane like the
flight section — and reports the gspmd-vs-eager step ratio recorded in
docs/benchmarks.md (the acceptance bar: gspmd's step time <= eager's,
i.e. step_time_ratio_gspmd_vs_eager <= 1.0).  The gspmd leg runs through
ops/hlo_inspect.instrument, and its compiled-collective inventory (kinds
plus analytic ring-model bytes) is stamped into the summary line as
provenance for the numbers.

With --hlo-inspect an additional section reruns the gspmd-plane worker
with HOROVOD_HLO_INSPECT=0 vs 1 — interleaved, best-of-3 per config like
the flight section — and reports compiled-collective introspection's
step-throughput overhead.  The bar is <= 1%: inspection (one extra
lower + compile + module-text walk) happens once per trace signature at
warmup, never inside the timed step loop.

With --metrics an additional section reruns the cache_on configuration
with HOROVOD_METRICS=1 and reports the registry's negotiation-throughput
overhead against the metrics-off baseline (disabled is the baseline
itself: every instrumentation site is behind one relaxed bool load, so
disabled overhead is zero by construction).

With --flight-recorder an additional section runs the cache_on
configuration with HOROVOD_FLIGHT_RECORDER=off vs on — interleaved,
best-of-3 per config, because loopback wall clock is noisier than the
effect — and reports the always-on event black box's
negotiation-throughput overhead (the bar is <= 1%: a record is a handful
of relaxed atomic stores into a per-thread ring).

With --step-trace an additional section runs the cache_on configuration
with HOROVOD_STEP_TRACE=0 vs 1 (plus a third leg stacking
HOROVOD_METRICS=1 on top, the full CYCLE-trailer marker-2 payload) —
interleaved, best-of-3 per config like the flight section — and reports
the causal step tracer's negotiation-throughput overhead.  The bar is
<= 1% with the cockpit disabled: span capture is relaxed atomic adds at
already-instrumented sites, and the per-cycle trailer is 6 extra i64s.

With --fleet-telemetry an additional section runs the cache_on
configuration with HOROVOD_METRICS=1 and HOROVOD_FLEET_TELEMETRY=0 vs 1 —
interleaved, best-of-3 per config like the flight section — and reports
the v11 fleet telemetry plane's negotiation-throughput overhead: the
delta/varint sketch section every rank appends to its CYCLE frame, the
coordinator-side sketch merge, and the ~1 Hz history/goodput/sentinel
tick.  The bar is <= 1%; the metrics-on baseline isolates the plane's own
cost from the registry's.

With --np-sweep N,N,... the tool instead sweeps job sizes over fake
multi-host topologies (4 ranks per fake host) and prints the O(n)-vs-
O(hosts)-vs-O(fanout) table behind the leader tree: coordinator inbound
control messages and bytes per negotiation cycle — flat, auto-depth tree
(v9 shape below 32 hosts), and the tree forced three levels deep
(HOROVOD_CONTROL_TREE_DEPTH=3, the v12 adaptive-depth plane) — from the
ctrl_msgs_/ctrl_bytes_ counters normalised by cycle_count.  Results are
recorded in docs/benchmarks.md.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _worker(steps: int, tensors: int):
    import time

    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu import mpi_ops
    from horovod_tpu.context import HorovodContext

    hvd.init(build_mesh=False)
    grads = [np.full(64, float(i), np.float32) for i in range(tensors)]

    def step(tag):
        hs = [mpi_ops.allreduce_async(g, name=f"grad.{i}", op=hvd.Sum)
              for i, g in enumerate(grads)]
        for h in hs:
            mpi_ops.synchronize(h)

    # Warmup: populate the response cache / reach steady state.
    for s in range(5):
        step(s)
    core = HorovodContext.instance().core
    stats0 = core.negotiation_stats() if hasattr(core, "negotiation_stats") \
        else None
    t0 = time.perf_counter()
    for s in range(steps):
        step(s)
    dt = time.perf_counter() - t0
    result = {"rank": hvd.rank(), "steps_per_s": steps / dt,
              "tensor_ops_per_s": steps * len(grads) / dt}
    if stats0 is not None:
        stats1 = core.negotiation_stats()
        # Announce direction (worker -> coordinator): where the cache's
        # (id, handle) pairs replace full request metadata.  The recv
        # direction is the response list, identical in both configs.
        result["announce_bytes_per_step"] = (
            (stats1["ctrl_sent"] - stats0["ctrl_sent"]) / steps)
        result["ctrl_bytes_per_step"] = (
            (stats1["ctrl_sent"] + stats1["ctrl_recv"]
             - stats0["ctrl_sent"] - stats0["ctrl_recv"]) / steps)
    hvd.shutdown()
    return result


def run_config(name: str, env: dict, np_: int, steps: int, tensors: int):
    from horovod_tpu.runner import run

    full_env = {"JAX_PLATFORMS": "cpu", **env}
    results = run(_worker, args=(steps, tensors), np=np_, env=full_env,
                  stream_prefix=False)
    agg = {
        "config": name,
        "np": np_,
        "steps_per_s": round(min(r["steps_per_s"] for r in results), 2),
        "tensor_ops_per_s": round(
            min(r["tensor_ops_per_s"] for r in results), 1),
    }
    per_step = [r.get("ctrl_bytes_per_step") for r in results[1:]]
    if per_step and per_step[0] is not None:
        # Worker ranks only: the coordinator's ctrl traffic counts every
        # worker's frames and would double-book.
        agg["worker_ctrl_bytes_per_step"] = round(max(per_step), 1)
        agg["worker_announce_bytes_per_step"] = round(
            max(r["announce_bytes_per_step"] for r in results[1:]), 1)
    print(json.dumps(agg), flush=True)
    return agg


def _wire_worker(steps: int, elems: int):
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.context import HorovodContext

    hvd.init(build_mesh=False)
    r, s = hvd.rank(), hvd.size()
    x = ((np.arange(elems) % 251) + r).astype(np.float32)
    exact = sum(((np.arange(elems) % 251) + rr).astype(np.float64)
                for rr in range(s))
    core = HorovodContext.instance().core
    hvd.allreduce(x, op=hvd.Sum, name="wb.warm")
    hvd.barrier()
    s0 = core.data_plane_stats()
    max_err = 0.0
    import time

    t0 = time.perf_counter()
    for i in range(steps):
        out = np.asarray(hvd.allreduce(x, op=hvd.Sum, name=f"wb.{i}"),
                         dtype=np.float64)
        max_err = max(max_err, float(np.max(np.abs(out - exact))))
    dt = time.perf_counter() - t0
    s1 = core.data_plane_stats()
    hvd.barrier()
    hvd.shutdown()
    return {"rank": r, "steps_per_s": steps / dt, "max_abs_err": max_err,
            "xhost_bytes_per_step":
                (s1["data_sent_xhost"] - s0["data_sent_xhost"]) / steps,
            "raw_xhost_bytes_per_step":
                (s1["data_raw_xhost"] - s0["data_raw_xhost"]) / steps}


def run_wire_config(codec: str, np_: int, steps: int, elems: int):
    from horovod_tpu.runner import run

    env = {"JAX_PLATFORMS": "cpu", "HOROVOD_HIER_FAKE_HOSTS": "2",
           "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
           "HOROVOD_WIRE_COMPRESSION": codec}
    results = run(_wire_worker, args=(steps, elems), np=np_, env=env,
                  stream_prefix=False)
    agg = {
        "config": f"wire_{codec}",
        "np": np_,
        "payload_bytes": elems * 4,
        "steps_per_s": round(min(r["steps_per_s"] for r in results), 2),
        "xhost_bytes_per_step": round(
            sum(r["xhost_bytes_per_step"] for r in results), 1),
        "raw_xhost_bytes_per_step": round(
            sum(r["raw_xhost_bytes_per_step"] for r in results), 1),
        "max_abs_err": max(r["max_abs_err"] for r in results),
    }
    print(json.dumps(agg), flush=True)
    return agg


def _device_worker(steps: int, elems: int):
    import time

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    import horovod_tpu as hvd
    import horovod_tpu.ops.quantize as qz

    hvd.init(build_mesh=False)
    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("q",))

    def fn(shard):
        return hvd.allreduce(shard, axis_name="q", op=hvd.Sum)

    try:
        sm = shard_map(fn, mesh=mesh, in_specs=P("q"), out_specs=P("q"),
                       check_rep=False)
    except TypeError:  # newer jax renamed the kwarg
        sm = shard_map(fn, mesh=mesh, in_specs=P("q"), out_specs=P("q"),
                       check_vma=False)
    jitted = jax.jit(sm)

    per_dev = max(1, elems // len(devs))
    x_np = (((np.arange(len(devs) * per_dev) % 509) / 509.0 - 0.5)
            .astype(np.float32).reshape(len(devs), per_dev))
    exact = np.sum(x_np.astype(np.float64), axis=0)
    x = jnp.asarray(x_np)

    # The byte counters tick at trace time (once per compile), so the
    # delta around the warmup call IS one step's ring volume.
    qz.reset_device_byte_counters()
    out = np.asarray(jitted(x))
    raw, enc = qz.device_byte_counters()
    max_err = float(np.max(np.abs(out.astype(np.float64) - exact)))

    t0 = time.perf_counter()
    for _ in range(steps):
        jitted(x).block_until_ready()
    dt = time.perf_counter() - t0

    hvd.shutdown()
    return {"steps_per_s": steps / dt, "max_abs_err": max_err,
            "device_raw_bytes_per_step": raw,
            "device_encoded_bytes_per_step": enc}


def run_device_config(codec: str, steps: int, elems: int,
                      schedule: str | None = None):
    from horovod_tpu.runner import run

    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           "HOROVOD_WIRE_COMPRESSION_MIN_BYTES": "4096"}
    if codec != "none":
        env["HOROVOD_WIRE_COMPRESSION"] = f"device={codec}"
    if schedule:
        env["HOROVOD_DEVICE_SCHEDULE"] = schedule
    results = run(_device_worker, args=(steps, elems), np=1, env=env,
                  stream_prefix=False)
    agg = dict(results[0])
    name = f"device_{codec}" + (f"_{schedule}" if schedule else "")
    agg.update({"config": name, "payload_bytes": elems * 4,
                "steps_per_s": round(agg["steps_per_s"], 2)})
    print(json.dumps(agg), flush=True)
    return agg


def _plane_worker(steps: int, elems: int, plane: str):
    import time

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    import horovod_tpu as hvd
    from horovod_tpu.ops import gspmd_plane as gp
    from horovod_tpu.ops import hlo_inspect as hi
    from horovod_tpu.optimizer import DistributedOptimizer

    hvd.init(build_mesh=False)
    devs = jax.devices()
    n = len(devs)

    # One SGD step on an elementwise model: the weight vector IS the
    # collective payload (elems fp32), the batch is sharded n ways.  An
    # elementwise (not matmul) backward keeps the comparison about the
    # planes: the SPMD partitioner lowers a matmul's weight gradient
    # through a post-all-reduce transpose copy on the CPU backend, a
    # partitioner artifact that would swamp the collective delta.
    d = max(8, elems)
    batch = 2 * n
    rs = np.random.RandomState(0)
    x_np = rs.randn(batch, d).astype(np.float32)
    y_np = rs.randn(batch, d).astype(np.float32)
    params = {"w": jnp.zeros((d,), jnp.float32)}

    def loss(p, xs, ys):
        return jnp.mean((xs * p["w"] - ys) ** 2)

    if plane == "gspmd":
        # gspmd convention: plain jit, batch-sharded inputs, global-mean
        # loss — GSPMD inserts and schedules the gradient reduction.
        mesh = gp.build_gspmd_mesh()
        tx = DistributedOptimizer(optax.sgd(0.01), plane="gspmd")
        x = jax.device_put(jnp.asarray(x_np),
                           NamedSharding(mesh, P(gp.BATCH_AXIS)))
        y = jax.device_put(jnp.asarray(y_np),
                           NamedSharding(mesh, P(gp.BATCH_AXIS)))

        @jax.jit
        def step(p, s, xs, ys):
            g = jax.grad(loss)(p, xs, ys)
            u, s2 = tx.update(g, s, p)
            return optax.apply_updates(p, u), s2

        # Compiled-collective introspection rides the warmup compile
        # (once per trace signature); with HOROVOD_HLO_INSPECT=0 this
        # returns ``step`` unchanged — the --hlo-inspect baseline.
        step = hi.instrument(step, label="bench_plane")
    else:
        # eager convention: shard_map with the bound mesh axis, explicit
        # psum-average inside the optimizer.  Inputs are committed
        # sharded exactly like the gspmd leg — neither plane pays a
        # per-call scatter.
        mesh = Mesh(np.asarray(devs), ("hvd",))
        tx = DistributedOptimizer(optax.sgd(0.01), plane="eager")
        x = jax.device_put(jnp.asarray(x_np),
                           NamedSharding(mesh, P("hvd")))
        y = jax.device_put(jnp.asarray(y_np),
                           NamedSharding(mesh, P("hvd")))

        def shard_step(p, s, xs, ys):
            g = jax.grad(loss)(p, xs, ys)
            u, s2 = tx.update(g, s, p)
            return optax.apply_updates(p, u), s2

        try:
            sm = shard_map(shard_step, mesh=mesh,
                           in_specs=(P(), P(), P("hvd"), P("hvd")),
                           out_specs=(P(), P()), check_rep=False)
        except TypeError:  # newer jax renamed the kwarg
            sm = shard_map(shard_step, mesh=mesh,
                           in_specs=(P(), P(), P("hvd"), P("hvd")),
                           out_specs=(P(), P()), check_vma=False)
        step = jax.jit(sm)

    state = tx.init(params)
    p, s = step(params, state, x, y)  # compile outside the timed loop
    jax.tree_util.tree_leaves(p)[0].block_until_ready()

    t0 = time.perf_counter()
    for _ in range(steps):
        p, s = step(p, s, x, y)
    jax.tree_util.tree_leaves(p)[0].block_until_ready()
    dt = time.perf_counter() - t0

    hvd.shutdown()
    res = {"steps_per_s": steps / dt, "plane": plane, "grad_bytes": d * 4}
    invs = [i for i in hi.inventories() if i.label == "bench_plane"]
    if invs:
        # Provenance: what XLA actually scheduled for this step (empty
        # when introspection is off or the plane resolved eager).
        inv = invs[-1]
        res["hlo"] = {"collectives": inv.collectives,
                      "kinds": inv.kind_counts(),
                      "raw_bytes": inv.raw_bytes,
                      "wire_bytes": inv.wire_bytes}
    return res


def run_plane_config(plane: str, steps: int, elems: int,
                     extra_env=None, tag: str = ""):
    from horovod_tpu.runner import run

    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    if extra_env:
        env.update(extra_env)
    results = run(_plane_worker, args=(steps, elems, plane), np=1, env=env,
                  stream_prefix=False)
    agg = dict(results[0])
    agg.update({"config": f"plane_{plane}{tag}",
                "steps_per_s": round(agg["steps_per_s"], 2)})
    print(json.dumps(agg), flush=True)
    return agg


def _sweep_worker(steps: int, tensors: int):
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu import mpi_ops

    hvd.init(build_mesh=False)
    grads = [np.full(64, float(i), np.float32) for i in range(tensors)]

    def step():
        hs = [mpi_ops.allreduce_async(g, name=f"sw.{i}", op=hvd.Sum)
              for i, g in enumerate(grads)]
        for h in hs:
            mpi_ops.synchronize(h)

    for _ in range(5):  # steady state: response cache populated
        step()
    hvd.barrier()
    c0 = hvd.metrics()["counters"]
    for _ in range(steps):
        step()
    hvd.barrier()
    c1 = hvd.metrics()["counters"]
    rank = hvd.rank()
    hvd.shutdown()
    return {"rank": rank,
            "cycles": c1["cycle_count"] - c0["cycle_count"],
            "msgs_recv": c1["ctrl_msgs_recv"] - c0["ctrl_msgs_recv"],
            "msgs_sent": c1["ctrl_msgs_sent"] - c0["ctrl_msgs_sent"],
            "bytes_recv": c1["ctrl_bytes_recv"] - c0["ctrl_bytes_recv"],
            "bytes_sent": c1["ctrl_bytes_sent"] - c0["ctrl_bytes_sent"]}


def run_np_sweep(np_list, steps: int, tensors: int):
    """Coordinator control messages + bytes per cycle — flat vs the
    auto-depth tree vs the tree forced three levels deep — at each job
    size over fake hosts (4 consecutive ranks per host).  The lockstep
    makes messages/cycle a topology constant — (np-1) flat,
    (local-1)+(hosts-1) for the two-level tree, (local-1)+direct-children
    once a super layer absorbs leader clusters — so the per-cycle numbers
    are exact while bytes/cycle reflect the measured aggregate framing
    overhead."""
    from horovod_tpu.runner import run

    for np_ in np_list:
        hosts = max(2, np_ // 4)
        row = {"metric": "ctrl_plane_np_sweep", "np": np_, "hosts": hosts}
        modes = [("flat", "off", None), ("tree", "on", None)]
        if hosts >= 3:  # depth 3 needs >= 3 leaders to grow a super layer
            modes.append(("tree_d3", "on", "3"))
        for mode, tree, depth in modes:
            env = {"JAX_PLATFORMS": "cpu", "HOROVOD_METRICS": "1",
                   "HOROVOD_SHM_DISABLE": "1",
                   "HOROVOD_HIER_FAKE_HOSTS": str(hosts),
                   "HOROVOD_CONTROL_TREE": tree}
            if depth is not None:
                env["HOROVOD_CONTROL_TREE_DEPTH"] = depth
            results = run(_sweep_worker, args=(steps, tensors), np=np_,
                          env=env, stream_prefix=False)
            coord = next(r for r in results if r["rank"] == 0)
            cycles = max(coord["cycles"], 1)
            row[f"{mode}_msgs_per_cycle"] = round(
                coord["msgs_recv"] / cycles, 2)
            row[f"{mode}_bytes_per_cycle"] = round(
                coord["bytes_recv"] / cycles, 1)
        row["msgs_ratio"] = round(
            row["flat_msgs_per_cycle"]
            / max(row["tree_msgs_per_cycle"], 1e-9), 2)
        if "tree_d3_msgs_per_cycle" in row:
            row["msgs_ratio_d3"] = round(
                row["flat_msgs_per_cycle"]
                / max(row["tree_d3_msgs_per_cycle"], 1e-9), 2)
        print(json.dumps(row), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=4)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--tensors", type=int, default=50)
    ap.add_argument("--wire-compression", default=None,
                    choices=["bf16", "int8", "int4", "int8g"],
                    help="also benchmark the wire codec on a cross-host "
                         "(fake two-host, hierarchical) topology against "
                         "the fp32 baseline: bytes/step + max abs error")
    ap.add_argument("--wire-mb", type=float, default=4.0,
                    help="fp32 payload size for the wire benchmark (MiB)")
    ap.add_argument("--wire-steps", type=int, default=10)
    ap.add_argument("--device-codec", default=None,
                    choices=["int8", "int4", "int8g"],
                    help="also benchmark the in-jit device-plane codec "
                         "(HOROVOD_WIRE_COMPRESSION device= plane) over a "
                         "forced 8-device CPU host platform: encoded/raw "
                         "wire ratio, quantization error, steps/s vs the "
                         "uncompressed traced ring")
    ap.add_argument("--device-schedule", default=None,
                    choices=["auto", "ring", "bidi", "torus"],
                    help="ring topology for the device benchmark "
                         "(HOROVOD_DEVICE_SCHEDULE); implies the device "
                         "section with codec int8 if --device-codec is "
                         "not given")
    ap.add_argument("--device-mb", type=float, default=4.0,
                    help="fp32 payload size for the device benchmark (MiB)")
    ap.add_argument("--device-steps", type=int, default=20)
    ap.add_argument("--data-plane", action="store_true",
                    help="also measure one SGD train step under the eager "
                         "plane (shard_map + explicit psum) vs the gspmd "
                         "plane (sharded inputs, compiler-inserted "
                         "collectives) on the 8-device CPU mesh — "
                         "interleaved, best-of-3 — and report the "
                         "gspmd-vs-eager step ratio")
    ap.add_argument("--hlo-inspect", action="store_true",
                    help="also measure compiled-collective introspection's "
                         "step overhead: the gspmd-plane worker with "
                         "HOROVOD_HLO_INSPECT=0 vs 1, interleaved "
                         "best-of-3 (<= 1%% is the acceptance bar — "
                         "inspection runs once per trace, never per step)")
    ap.add_argument("--metrics", action="store_true",
                    help="also measure the metrics registry's negotiation "
                         "overhead: cache_on rerun with HOROVOD_METRICS=1, "
                         "steps/s ratio vs the metrics-off baseline")
    ap.add_argument("--step-trace", action="store_true",
                    help="also measure causal step tracing's negotiation "
                         "overhead (off vs on vs on+metrics, interleaved "
                         "best-of-3; cockpit stays disabled)")
    ap.add_argument("--flight-recorder", action="store_true",
                    help="also measure the flight recorder's negotiation "
                         "overhead: cache_on with the recorder off vs on, "
                         "steps/s ratio (<= 1%% is the acceptance bar)")
    ap.add_argument("--fleet-telemetry", action="store_true",
                    help="also measure the v11 fleet telemetry plane's "
                         "negotiation overhead: metrics-on with "
                         "HOROVOD_FLEET_TELEMETRY=0 vs 1, interleaved "
                         "best-of-3 (<= 1%% is the acceptance bar)")
    ap.add_argument("--np-sweep", default=None, metavar="N,N,...",
                    help="run ONLY the control-plane scaling sweep: "
                         "coordinator ctrl messages + bytes per cycle — "
                         "flat vs auto-depth tree vs forced depth-3 "
                         "(v12) — at each np over fake hosts "
                         "(4 ranks/host)")
    ap.add_argument("--sweep-steps", type=int, default=30)
    args = ap.parse_args()
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    if args.np_sweep:
        run_np_sweep([int(n) for n in args.np_sweep.split(",")],
                     args.sweep_steps, args.tensors)
        return

    cache_on = run_config("cache_on", {}, args.np, args.steps, args.tensors)
    cache_off = run_config("cache_off", {"HOROVOD_CACHE_CAPACITY": "0"},
                           args.np, args.steps, args.tensors)
    fusion_off = run_config(
        "fusion_off", {"HOROVOD_FUSION_THRESHOLD": "1"},
        args.np, args.steps, args.tensors)

    summary = {
        "metric": "negotiation_cache_speedup",
        "steps_ratio_cache_on_vs_off": round(
            cache_on["steps_per_s"] / cache_off["steps_per_s"], 3),
        "steps_ratio_cache_on_vs_fusion_off": round(
            cache_on["steps_per_s"] / fusion_off["steps_per_s"], 3),
    }
    if "worker_ctrl_bytes_per_step" in cache_on and \
            "worker_ctrl_bytes_per_step" in cache_off:
        summary["ctrl_bytes_ratio_on_vs_off"] = round(
            cache_on["worker_ctrl_bytes_per_step"]
            / max(cache_off["worker_ctrl_bytes_per_step"], 1.0), 3)
        summary["announce_bytes_ratio_on_vs_off"] = round(
            cache_on["worker_announce_bytes_per_step"]
            / max(cache_off["worker_announce_bytes_per_step"], 1.0), 3)
    print(json.dumps(summary), flush=True)

    if args.metrics:
        metrics_on = run_config("cache_on_metrics", {"HOROVOD_METRICS": "1"},
                                args.np, args.steps, args.tensors)
        ratio = metrics_on["steps_per_s"] / max(cache_on["steps_per_s"], 1e-9)
        print(json.dumps({
            "metric": "metrics_overhead",
            "steps_ratio_on_vs_off": round(ratio, 3),
            "overhead_pct": round(max(0.0, (1.0 - ratio)) * 100.0, 2),
        }), flush=True)

    if args.flight_recorder:
        # Loopback wall clock is scheduler-noise-dominated: one config's
        # steps/s varies far more run-to-run than the <= 1% bar being
        # measured.  Interleave the pair and keep the best of three — the
        # fastest (least-perturbed) run per config bounds its true cost.
        best_off = best_on = 0.0
        for i in range(3):
            flight_off = run_config(
                f"cache_on_flight_off_r{i}",
                {"HOROVOD_FLIGHT_RECORDER": "off"},
                args.np, args.steps, args.tensors)
            flight_on = run_config(
                f"cache_on_flight_on_r{i}", {"HOROVOD_FLIGHT_RECORDER": "1"},
                args.np, args.steps, args.tensors)
            best_off = max(best_off, flight_off["steps_per_s"])
            best_on = max(best_on, flight_on["steps_per_s"])
        ratio = best_on / max(best_off, 1e-9)
        print(json.dumps({
            "metric": "flight_recorder_overhead",
            "best_of": 3,
            "steps_ratio_on_vs_off": round(ratio, 3),
            "overhead_pct": round(max(0.0, (1.0 - ratio)) * 100.0, 2),
        }), flush=True)

    if args.step_trace:
        # Same interleaved best-of-3 discipline as the flight section:
        # the <= 1% bar is far below loopback scheduler noise.  The third
        # leg stacks metrics on so the full marker-2 CYCLE trailer
        # (7 metric + 6 step-trace i64s) is priced too.
        best_off = best_on = best_both = 0.0
        for i in range(3):
            trace_off = run_config(
                f"cache_on_trace_off_r{i}", {"HOROVOD_STEP_TRACE": "0"},
                args.np, args.steps, args.tensors)
            trace_on = run_config(
                f"cache_on_trace_on_r{i}", {"HOROVOD_STEP_TRACE": "1"},
                args.np, args.steps, args.tensors)
            trace_both = run_config(
                f"cache_on_trace_metrics_r{i}",
                {"HOROVOD_STEP_TRACE": "1", "HOROVOD_METRICS": "1"},
                args.np, args.steps, args.tensors)
            best_off = max(best_off, trace_off["steps_per_s"])
            best_on = max(best_on, trace_on["steps_per_s"])
            best_both = max(best_both, trace_both["steps_per_s"])
        ratio = best_on / max(best_off, 1e-9)
        print(json.dumps({
            "metric": "step_trace_overhead",
            "best_of": 3,
            "steps_ratio_on_vs_off": round(ratio, 3),
            "overhead_pct": round(max(0.0, (1.0 - ratio)) * 100.0, 2),
            "steps_ratio_with_metrics_vs_off": round(
                best_both / max(best_off, 1e-9), 3),
        }), flush=True)

    if args.fleet_telemetry:
        # Interleaved best-of-3 against a metrics-ON baseline: the plane
        # rides the metrics plumbing (sketches are captured from the
        # registry's histograms), so the delta being priced is the v11
        # sketch sections + coordinator merge + 1 Hz tick alone.
        best_off = best_on = 0.0
        for i in range(3):
            fleet_off = run_config(
                f"cache_on_fleet_off_r{i}",
                {"HOROVOD_METRICS": "1", "HOROVOD_FLEET_TELEMETRY": "0"},
                args.np, args.steps, args.tensors)
            fleet_on = run_config(
                f"cache_on_fleet_on_r{i}",
                {"HOROVOD_METRICS": "1", "HOROVOD_FLEET_TELEMETRY": "1"},
                args.np, args.steps, args.tensors)
            best_off = max(best_off, fleet_off["steps_per_s"])
            best_on = max(best_on, fleet_on["steps_per_s"])
        ratio = best_on / max(best_off, 1e-9)
        print(json.dumps({
            "metric": "fleet_telemetry_overhead",
            "best_of": 3,
            "steps_ratio_on_vs_off": round(ratio, 3),
            "overhead_pct": round(max(0.0, (1.0 - ratio)) * 100.0, 2),
        }), flush=True)

    if args.data_plane:
        # Interleaved best-of-3 like the flight section: loopback wall
        # clock is noisier than the plane delta being measured.  Same
        # train step, both calling conventions (docs/architecture.md
        # "Three data planes"), sized by --device-mb / --device-steps.
        elems = int(args.device_mb * (1 << 20)) // 4
        best_eager = best_gspmd = 0.0
        hlo = None
        for _ in range(3):
            e = run_plane_config("eager", args.device_steps, elems)
            g = run_plane_config("gspmd", args.device_steps, elems)
            best_eager = max(best_eager, e["steps_per_s"])
            best_gspmd = max(best_gspmd, g["steps_per_s"])
            hlo = g.get("hlo") or hlo
        print(json.dumps({
            "metric": "data_plane",
            "best_of": 3,
            "steps_ratio_gspmd_vs_eager": round(
                best_gspmd / max(best_eager, 1e-9), 3),
            "step_time_ratio_gspmd_vs_eager": round(
                best_eager / max(best_gspmd, 1e-9), 3),
            # Compiled-collective provenance for the gspmd leg (None on
            # a HOROVOD_HLO_INSPECT=0 run).
            "hlo": hlo,
        }), flush=True)

    if args.hlo_inspect:
        # Interleaved best-of-3 like the flight section: introspection's
        # lower+compile+parse rides the warmup trace, so the timed loop
        # must not move — <= 1% is the bar.
        elems = int(args.device_mb * (1 << 20)) // 4
        best_off = best_on = 0.0
        hlo = None
        for i in range(3):
            h_off = run_plane_config(
                "gspmd", args.device_steps, elems,
                extra_env={"HOROVOD_HLO_INSPECT": "0"},
                tag=f"_hlo_off_r{i}")
            h_on = run_plane_config(
                "gspmd", args.device_steps, elems,
                extra_env={"HOROVOD_HLO_INSPECT": "1"},
                tag=f"_hlo_on_r{i}")
            best_off = max(best_off, h_off["steps_per_s"])
            best_on = max(best_on, h_on["steps_per_s"])
            hlo = h_on.get("hlo") or hlo
        ratio = best_on / max(best_off, 1e-9)
        print(json.dumps({
            "metric": "hlo_inspect_overhead",
            "best_of": 3,
            "steps_ratio_on_vs_off": round(ratio, 3),
            "overhead_pct": round(max(0.0, (1.0 - ratio)) * 100.0, 2),
            "hlo": hlo,
        }), flush=True)

    if args.wire_compression:
        elems = int(args.wire_mb * (1 << 20)) // 4
        base = run_wire_config("none", args.np, args.wire_steps, elems)
        comp = run_wire_config(args.wire_compression, args.np,
                               args.wire_steps, elems)
        print(json.dumps({
            "metric": "wire_compression",
            "codec": args.wire_compression,
            "xhost_bytes_ratio_vs_fp32": round(
                comp["xhost_bytes_per_step"]
                / max(base["xhost_bytes_per_step"], 1.0), 3),
            "wire_vs_raw_ratio": round(
                comp["xhost_bytes_per_step"]
                / max(comp["raw_xhost_bytes_per_step"], 1.0), 3),
            "max_abs_err": comp["max_abs_err"],
            "steps_ratio_vs_fp32": round(
                comp["steps_per_s"] / max(base["steps_per_s"], 1e-9), 3),
        }), flush=True)

    if args.device_codec or args.device_schedule:
        codec = args.device_codec or "int8"
        elems = int(args.device_mb * (1 << 20)) // 4
        dbase = run_device_config("none", args.device_steps, elems)
        dcomp = run_device_config(codec, args.device_steps, elems,
                                  schedule=args.device_schedule)
        assert dbase["device_raw_bytes_per_step"] == 0, \
            "baseline must not touch the device codec"
        print(json.dumps({
            "metric": "device_codec",
            "codec": codec,
            "schedule": args.device_schedule or "auto",
            "device_encoded_vs_raw_ratio": round(
                dcomp["device_encoded_bytes_per_step"]
                / max(dcomp["device_raw_bytes_per_step"], 1.0), 3),
            "max_abs_err": dcomp["max_abs_err"],
            "steps_ratio_vs_fp32": round(
                dcomp["steps_per_s"] / max(dbase["steps_per_s"], 1e-9), 3),
        }), flush=True)


if __name__ == "__main__":
    main()
