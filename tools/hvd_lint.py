#!/usr/bin/env python
"""hvd_lint: cross-layer ABI / env / protocol consistency checker.

The framework's correctness hinges on four hand-mirrored seams, each of
which drifts silently (a mismatch corrupts data or loses a knob, it does
not crash):

  ABI       the ``extern "C"`` surface in cpp/core_api.cc  vs  the ctypes
            argtypes/restype declarations in _core.py
  env       the HOROVOD_* variables read anywhere (C++ getenv, Python
            os.environ)  vs  the central parser utils/env.py and the doc
            tables
  protocol  kProtocolVersion / frame tags / wire-codec ids in C++  vs  the
            Python mirrors (runtime.PROTOCOL_VERSION, _core.py codec map,
            env.py codec names) and the docs
  flight    the flight-recorder event-type table, kept in four places:
            flight_recorder.h's FlightType enum, flight_recorder.cc's
            kFlightTypesLegend JSON, tools/postmortem.py's FLIGHT_TYPES
            fallback, and the marked table in docs/observability.md

Three further passes turn the C++ spine's concurrency discipline — the
invariants TSan can only sample dynamically — into static, fail-on-drift
checks:

  atomic    every std::atomic load/store/RMW in the always-on hot-path
            files (ATOMIC_HOT_FILES) must name an explicit memory_order;
            implicit seq_cst is a finding, escapable per site with
            `// lint: seq_cst-ok(<reason>)` (stale hatches are findings)
  lockorder mutex acquisitions per function in LOCKORDER_FILES, closed
            over the intra-file call graph into an inter-mutex acquisition
            graph; any cycle (or same-mutex re-acquisition) is reported as
            a potential deadlock with witness paths
  sigsafe   from the fatal-signal handlers installed in flight_recorder.cc,
            walk the intra-file call graph and flag any reachable call
            outside the async-signal-safe allowlist, any `new`, and any
            lock — statically pinning the PR 8 signal-dump claim;
            per-site escape: `// lint: sigsafe-ok(<reason>)`

Each pass is a pure text analysis (no build, no import of horovod_tpu), so
this runs in tier-1 CI on a bare checkout.  Output is a human report plus
optional JSON; findings are compared against a committed baseline
(tools/hvd_lint_baseline.json) so CI fails only on *new* findings.  The
baseline is empty by policy — pre-existing drift gets fixed, not baselined.

Usage:
    python tools/hvd_lint.py                # human report, exit 1 on new findings
    python tools/hvd_lint.py --json out.json
    python tools/hvd_lint.py --only atomic,lockorder   # subset, timed
    python tools/hvd_lint.py --update-baseline
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# Whitelists.  Every entry is a deliberate decision; the lint enforces that
# the lists stay honest in both directions (an entry that no longer matches
# reality is itself a finding).
# ---------------------------------------------------------------------------

# Symbols whose Python binding deliberately tolerates an old .so that
# predates them (declared inside try/except, callers hasattr-guard):
# the checker allows conditional declaration but still verifies types.
OLD_ABI_TOLERANT = {"hvd_metrics_dump", "hvd_data_plane_stats2",
                    "hvd_fault_spec_check", "hvd_ctrl_plane_stats",
                    "hvd_flight_record", "hvd_add_process_set2",
                    "hvd_device_plane_note", "hvd_device_plane_stats",
                    "hvd_autotune_qdev", "hvd_autotune_qsched",
                    "hvd_autotune_plane",
                    "hvd_migrate_note",
                    "hvd_elastic_generation_set", "hvd_step_trace",
                    "hvd_fleet_history",
                    "hvd_gspmd_plane_note", "hvd_gspmd_plane_stats",
                    "hvd_step_trace_note_plane"}

# HOROVOD_* variables read directly by C++ getenv (not routed through
# utils/env.py): plane/topology knobs consumed below the ctypes ABI, where
# threading them through hvd_init would widen the init signature for no
# behavioural gain.  Each MUST be documented in a doc table.
NATIVE_READ_VARS = {
    "HOROVOD_SHM_DISABLE",
    "HOROVOD_RING_CHUNK_BYTES",
    "HOROVOD_SOCKET_BUFFER_BYTES",
    "HOROVOD_HIER_FAKE_HOSTS",
    "HOROVOD_HOSTNAME",
    "HOROVOD_WIRE_COMPRESSION_MIN_BYTES",
    "HOROVOD_METRICS_REPORT_SECONDS",
    "HOROVOD_STRAGGLER_SKEW",
    "HOROVOD_STRAGGLER_MIN_MS",
    "HOROVOD_FAULT_INJECT",
    "HOROVOD_ABORT_PROPAGATION_TIMEOUT",
    "HOROVOD_RENDEZVOUS_RETRIES",
    "HOROVOD_RENDEZVOUS_BACKOFF_BASE_MS",
    "HOROVOD_CONTROL_TREE",
    "HOROVOD_CTRL_TREE_FANOUT",
    "HOROVOD_CONTROL_TREE_DEPTH",
    "HOROVOD_RENDEZVOUS_ACCEPTORS",
    "HOROVOD_FLEET_TELEMETRY",
    "HOROVOD_SENTINEL_ZSCORE",
}

# Public knobs read in Python outside utils/env.py (module-scope or
# launcher-time concerns that never reach the core Config).  Each MUST be
# documented in a doc table.
PY_DIRECT_VARS = {
    "HOROVOD_DEVICE_PLANE",
    "HOROVOD_EXECUTOR_LANES",
    "HOROVOD_LOG_TIMESTAMP",
    "HOROVOD_SSH_COMMAND",
    "HOROVOD_TPU_WORKERS",
    "HOROVOD_TPU_PROBE_PORT",
    "HOROVOD_LSF_INCLUDE_LAUNCH_HOST",
    "HOROVOD_JAX_DISTRIBUTED",
    "HOROVOD_JAX_COORDINATOR",
    "HOROVOD_ELASTIC_DISCOVERY_INTERVAL",
    "HOROVOD_ELASTIC_FAST_FAILURE_SECS",
    "HOROVOD_ELASTIC_BLACKLIST_FAILURES",
    "HOROVOD_ELASTIC_BLACKLIST_BASE_SECS",
    "HOROVOD_AUTOPILOT",
    "HOROVOD_AUTOPILOT_EVICT_WINDOWS",
    "HOROVOD_AUTOPILOT_MIN_NP",
    "HOROVOD_AUTOPILOT_COOLDOWN_SECS",
}

# Infrastructure plumbing set by one launcher component and read by
# another (secrets, worker identity, rendezvous bootstrap).  Exempt from
# the doc-table requirement — they are not user knobs.
INTERNAL_VARS = {
    "HOROVOD_ELASTIC_SECRET",
    "HOROVOD_ELASTIC_WORKER_ID",
    "HOROVOD_ELASTIC_GENERATION",
    "HOROVOD_ELASTIC_COORD_ADDR",
    "HOROVOD_ELASTIC_COORD_PORT",
    "HOROVOD_PROBE_SECRET",
    "HOROVOD_TPU_METADATA_URL",
    "HOROVOD_RANK_FROM_JSRUN",
    # Assigned per generation by the elastic driver; the coordinator's
    # loopback policy listener binds it.  Operators never set it by hand.
    "HOROVOD_AUTOPILOT_PORT",
    # Same contract for the live-cockpit endpoint: the driver hands rank 0
    # one sticky port so SSE clients survive re-formations.  The user-facing
    # switch is HOROVOD_COCKPIT; the port is driver plumbing.
    "HOROVOD_COCKPIT_PORT",
}


@dataclasses.dataclass
class Finding:
    pass_name: str  # one of PASS_NAMES ("abi", "env", ..., "sigsafe")
    key: str        # stable id, e.g. "ABI-ARITY:hvd_init"
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# ABI pass
# ---------------------------------------------------------------------------

# C++ parameter/return type -> the ctypes declaration _core.py must use.
CTYPE_OF = {
    "int": "c_int",
    "long long": "c_longlong",
    "double": "c_double",
    "char*": "c_char_p",
    "void*": "c_void_p",
    "void**": "POINTER(c_void_p)",
    "long long*": "POINTER(c_longlong)",
    "int*": "POINTER(c_int)",
}


def _normalize_cpp_type(decl: str) -> str:
    """'const long long* slice_counts' -> 'long long*' (identifier dropped)."""
    decl = decl.strip()
    m = re.match(r"^(.*?)\s*\b[A-Za-z_]\w*$", decl)
    if m and m.group(1).strip():
        decl = m.group(1)
    decl = decl.replace("const", " ")
    decl = re.sub(r"\s*\*\s*", "*", decl)     # glue stars to the type
    decl = re.sub(r"\s+", " ", decl).strip()
    return decl


def parse_extern_c(cpp_text: str) -> Dict[str, Tuple[str, List[str]]]:
    """Exported hvd_* symbols from core_api.cc: name -> (ret, [param types]).

    Types are normalized C++ ('long long*'); map through CTYPE_OF to get the
    expected ctypes declaration.
    """
    start = cpp_text.find('extern "C"')
    if start < 0:
        raise ValueError('no extern "C" block found')
    block = cpp_text[start:]
    out: Dict[str, Tuple[str, List[str]]] = {}
    for m in re.finditer(
            r'(?:^|\n)\s*((?:const\s+)?[A-Za-z_][\w ]*?[\s*]+)(hvd_\w+)'
            r'\s*\(([^)]*)\)\s*\{', block):
        ret_raw, name, params_raw = m.groups()
        ret = re.sub(r"\s*\*\s*", "*", ret_raw.replace("const", " "))
        ret = re.sub(r"\s+", " ", ret).strip()
        params_raw = " ".join(params_raw.split())
        params: List[str] = []
        if params_raw and params_raw != "void":
            params = [_normalize_cpp_type(p) for p in params_raw.split(",")]
        out[name] = (ret, params)
    return out


def parse_ctypes_decls(py_text: str) -> Dict[str, dict]:
    """argtypes/restype assignments from _core.py's _declare()."""
    decls: Dict[str, dict] = {}
    for m in re.finditer(r"lib\.(hvd_\w+)\.restype\s*=\s*([^\n]+)", py_text):
        name, val = m.group(1), m.group(2).strip()
        decls.setdefault(name, {})["restype"] = val.replace("c.", "")
    for m in re.finditer(r"lib\.(hvd_\w+)\.argtypes\s*=\s*\[(.*?)\]",
                         py_text, re.S):
        name, body = m.groups()
        args = [p.group(0).replace("c.", "")
                for p in re.finditer(r"c\.POINTER\(c\.\w+\)|c\.\w+", body)]
        decls.setdefault(name, {})["argtypes"] = args
    return decls


def parse_lib_calls(py_texts: Dict[str, str]) -> Dict[str, List[str]]:
    """lib.hvd_* / _lib.hvd_* attribute references per symbol -> [files]."""
    calls: Dict[str, List[str]] = {}
    for path, text in py_texts.items():
        # strip the declaration site so _declare() assignments don't count
        body = re.sub(r"lib\.hvd_\w+\.(?:argtypes|restype)[^\n]*", "", text)
        for m in re.finditer(r"\b_?lib\.(hvd_\w+)", body):
            calls.setdefault(m.group(1), []).append(path)
    return calls


def abi_pass(cpp_text: str, py_texts: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    exports = parse_extern_c(cpp_text)
    core_py = py_texts.get("horovod_tpu/_core.py", "")
    decls = parse_ctypes_decls(core_py)
    calls = parse_lib_calls(py_texts)

    for name, (ret, params) in sorted(exports.items()):
        decl = decls.get(name)
        if decl is None:
            findings.append(Finding(
                "abi", f"ABI-UNDECLARED:{name}",
                f"{name} is exported by core_api.cc but has no "
                f"argtypes/restype declaration in _core.py"))
            continue
        argtypes = decl.get("argtypes")
        if argtypes is not None:
            expected = [CTYPE_OF.get(p, f"<unmapped:{p}>") for p in params]
            if len(argtypes) != len(expected):
                findings.append(Finding(
                    "abi", f"ABI-ARITY:{name}",
                    f"{name}: C++ takes {len(expected)} args, _core.py "
                    f"declares {len(argtypes)} argtypes"))
            else:
                for i, (got, want) in enumerate(zip(argtypes, expected)):
                    if got != want:
                        findings.append(Finding(
                            "abi", f"ABI-TYPE:{name}:{i}",
                            f"{name} arg {i}: C++ '{params[i]}' expects "
                            f"{want}, _core.py declares {got}"))
        elif params:
            findings.append(Finding(
                "abi", f"ABI-NOARGTYPES:{name}",
                f"{name} takes {len(params)} args but _core.py declares "
                f"no argtypes (ctypes would guess, int-truncating "
                f"pointers on LP64)"))
        restype = decl.get("restype")
        if restype is not None:
            want_ret = None if ret == "void" else CTYPE_OF.get(ret)
            if restype != (want_ret or "None"):
                findings.append(Finding(
                    "abi", f"ABI-RESTYPE:{name}",
                    f"{name}: C++ returns '{ret}' ({want_ret}), _core.py "
                    f"declares restype {restype}"))
        elif ret not in ("void", "int"):
            # ctypes defaults restype to c_int: silently truncates
            # long long returns and corrupts pointers.
            findings.append(Finding(
                "abi", f"ABI-RESTYPE:{name}",
                f"{name} returns '{ret}' but _core.py declares no restype "
                f"(ctypes default c_int truncates it)"))

    for name in sorted(set(decls) - set(exports)):
        findings.append(Finding(
            "abi", f"ABI-UNKNOWN:{name}",
            f"_core.py declares {name} which core_api.cc does not export"))
    for name, sites in sorted(calls.items()):
        if name not in exports:
            findings.append(Finding(
                "abi", f"ABI-UNKNOWN-CALL:{name}",
                f"{name} called ({sites[0]}) but not exported by "
                f"core_api.cc"))
        elif exports[name][1] and decls.get(name, {}).get("argtypes") is None:
            findings.append(Finding(
                "abi", f"ABI-CALLSITE:{name}",
                f"{name} called ({sites[0]}) with no argtypes declared"))
    return findings


# ---------------------------------------------------------------------------
# env pass
# ---------------------------------------------------------------------------

_VAR = r"HOROVOD_[A-Z0-9_]*[A-Z0-9](?![A-Z0-9_])"

# Read sites.  Writes (env["X"] = ...) are launcher plumbing and are not
# obligations; a token ending in '_' is a line-wrapped prefix, not a name.
_PY_READ_PATTERNS = [
    re.compile(r"os\.environ\.get\(\s*[\"'](" + _VAR + ")"),
    re.compile(r"os\.getenv\(\s*[\"'](" + _VAR + ")"),
    re.compile(r"\benviron\[\s*[\"'](" + _VAR + r")[\"']\s*\](?!\s*=[^=])"),
    re.compile(r"\benv\.get\(\s*[\"'](" + _VAR + ")"),
    re.compile(r"\bget_(?:bool|int|float)\(\s*[\"'](" + _VAR + ")"),
    re.compile(r"\b_env_number\(\s*\n?\s*[\"'](" + _VAR + ")"),
]
_CC_READ_PATTERN = re.compile(r"getenv\(\s*\"(" + _VAR + ")\"")


def collect_code_reads(py_files: Dict[str, str],
                       cc_files: Dict[str, str]) -> Tuple[Dict[str, List[str]],
                                                          Dict[str, List[str]]]:
    py_reads: Dict[str, List[str]] = {}
    cc_reads: Dict[str, List[str]] = {}
    for path, text in py_files.items():
        for pat in _PY_READ_PATTERNS:
            for m in pat.finditer(text):
                py_reads.setdefault(m.group(1), []).append(path)
    for path, text in cc_files.items():
        for m in _CC_READ_PATTERN.finditer(text):
            cc_reads.setdefault(m.group(1), []).append(path)
    return py_reads, cc_reads


def parse_env_py(env_py_text: str) -> Tuple[set, set]:
    """(parsed, ignored) variable sets from utils/env.py.

    'parsed' is every HOROVOD_* token in the file outside the IGNORED_VARS
    tuple — the file is the single source of truth, so a mention there IS
    the central registration.
    """
    m = re.search(r"IGNORED_VARS\s*=\s*\((.*?)\)", env_py_text, re.S)
    ignored = set(re.findall(_VAR, m.group(1))) if m else set()
    body = env_py_text
    if m:
        body = body[:m.start(1)] + body[m.end(1):]
    parsed = set(re.findall(_VAR, body)) - ignored
    return parsed, ignored


def env_pass(py_files: Dict[str, str], cc_files: Dict[str, str],
             doc_files: Dict[str, str],
             native_read_vars: Optional[set] = None,
             py_direct_vars: Optional[set] = None,
             internal_vars: Optional[set] = None) -> List[Finding]:
    native_read_vars = (NATIVE_READ_VARS if native_read_vars is None
                        else native_read_vars)
    py_direct_vars = PY_DIRECT_VARS if py_direct_vars is None else py_direct_vars
    internal_vars = INTERNAL_VARS if internal_vars is None else internal_vars

    findings: List[Finding] = []
    env_py = py_files.get("horovod_tpu/utils/env.py", "")
    parsed, ignored = parse_env_py(env_py)
    py_reads, cc_reads = collect_code_reads(py_files, cc_files)

    table_rows: set = set()
    doc_mentions: set = set()
    for _, text in doc_files.items():
        for line in text.splitlines():
            vars_here = set(re.findall(_VAR, line))
            doc_mentions |= vars_here
            if line.lstrip().startswith("|"):
                table_rows |= vars_here

    # 1. C++ getenv <-> native whitelist, exact both ways.
    for var in sorted(set(cc_reads) - native_read_vars):
        findings.append(Finding(
            "env", f"ENV-NATIVE-UNLISTED:{var}",
            f"C++ reads {var} ({cc_reads[var][0]}) but it is not in "
            f"hvd_lint's NATIVE_READ_VARS whitelist"))
    for var in sorted(native_read_vars - set(cc_reads)):
        findings.append(Finding(
            "env", f"ENV-NATIVE-STALE:{var}",
            f"{var} is whitelisted as native-read but no C++ getenv "
            f"reads it"))

    # 2. Every Python read is centrally parsed or explicitly whitelisted.
    known = parsed | ignored | native_read_vars | py_direct_vars | internal_vars
    for var, sites in sorted(py_reads.items()):
        if var not in known:
            findings.append(Finding(
                "env", f"ENV-UNMANAGED:{var}",
                f"{var} read in {sites[0]} but not parsed in utils/env.py, "
                f"not in IGNORED_VARS, and not whitelisted"))

    # 3. Whitelisted Python-direct vars must actually be read somewhere.
    for var in sorted(py_direct_vars - set(py_reads)):
        findings.append(Finding(
            "env", f"ENV-DIRECT-STALE:{var}",
            f"{var} is whitelisted as python-direct but nothing reads it"))

    # 4. Every public knob has a doc table row.
    public = (parsed | native_read_vars | py_direct_vars) - internal_vars
    for var in sorted(public - table_rows):
        findings.append(Finding(
            "env", f"ENV-UNDOCUMENTED:{var}",
            f"{var} is a public knob but appears in no markdown table row "
            f"in docs/ or README.md"))

    # 5. No doc may name a var no code knows.
    for var in sorted(doc_mentions - known):
        findings.append(Finding(
            "env", f"ENV-STALE-DOC:{var}",
            f"docs name {var} but no code reads, parses, ignores, or "
            f"whitelists it"))
    return findings


# ---------------------------------------------------------------------------
# protocol pass
# ---------------------------------------------------------------------------

def parse_protocol_constants(sc_text: str) -> Tuple[Optional[int],
                                                    Dict[str, int]]:
    """(kProtocolVersion, {kTagName: value}) from socket_controller.cc."""
    vm = re.search(r"kProtocolVersion\s*=\s*(\d+)\s*;", sc_text)
    version = int(vm.group(1)) if vm else None
    tags = {m.group(1): int(m.group(2), 0) for m in re.finditer(
        r"constexpr\s+int32_t\s+(kTag\w+)\s*=\s*(0[xX][0-9a-fA-F]+|\d+)\s*;",
        sc_text)}
    return version, tags


def parse_wire_codecs(wire_codec_text: str) -> Dict[str, int]:
    """{'none': 0, 'bf16': 1, 'int8': 2} from wire_codec.h's enum."""
    m = re.search(r"enum\s+class\s+WireCodec[^{]*\{(.*?)\}", wire_codec_text,
                  re.S)
    if not m:
        return {}
    return {em.group(1).lower(): int(em.group(2))
            for em in re.finditer(r"k(\w+)\s*=\s*(\d+)", m.group(1))}


def parse_py_codec_map(core_py_text: str) -> Dict[str, int]:
    """The {'none': 0, ...} literal _core.py passes into hvd_init."""
    m = re.search(r'\{[^{}]*"bf16"[^{}]*\}', core_py_text)
    if not m:
        return {}
    return {pm.group(1): int(pm.group(2))
            for pm in re.finditer(r'"(\w+)"\s*:\s*(\d+)', m.group(0))}


def protocol_pass(sc_text: str, wire_codec_text: str, core_py_text: str,
                  runtime_py_text: str, env_py_text: str,
                  doc_files: Dict[str, str],
                  quantize_py_text: str = "") -> List[Finding]:
    findings: List[Finding] = []
    version, tags = parse_protocol_constants(sc_text)
    if version is None:
        findings.append(Finding(
            "protocol", "PROTO-NO-VERSION",
            "kProtocolVersion not found in socket_controller.cc"))
        return findings

    # Python mirror.
    pm = re.search(r"^PROTOCOL_VERSION\s*=\s*(\d+)", runtime_py_text, re.M)
    if not pm:
        findings.append(Finding(
            "protocol", "PROTO-NO-MIRROR",
            "horovod_tpu/runtime.py defines no PROTOCOL_VERSION mirror of "
            "kProtocolVersion"))
    elif int(pm.group(1)) != version:
        findings.append(Finding(
            "protocol", "PROTO-VERSION-MIRROR",
            f"kProtocolVersion={version} but runtime.PROTOCOL_VERSION="
            f"{pm.group(1)}"))

    # Doc claims: every explicit kProtocolVersion mention must match, and
    # at least one doc must make the claim (so a bump is forced through
    # the docs).
    doc_claims = 0
    for path, text in sorted(doc_files.items()):
        for dm in re.finditer(r"kProtocolVersion\D{0,24}?(\d+)", text):
            doc_claims += 1
            if int(dm.group(1)) != version:
                findings.append(Finding(
                    "protocol", f"PROTO-VERSION-DOC:{path}",
                    f"{path} states kProtocolVersion={dm.group(1)} but C++ "
                    f"says {version}"))
    if doc_claims == 0:
        findings.append(Finding(
            "protocol", "PROTO-VERSION-UNDOCUMENTED",
            "no doc states the current kProtocolVersion (a bump would be "
            "invisible to readers)"))

    # Frame tags: unique values, fence family above the SockBarrier metric
    # threshold (kTagShmSize), op tags below it, and >=0x100 spacing so
    # per-round (+k) and per-segment (+s) offsets cannot collide.
    by_value: Dict[int, List[str]] = {}
    for name, value in tags.items():
        by_value.setdefault(value, []).append(name)
    for value, names in sorted(by_value.items()):
        if len(names) > 1:
            findings.append(Finding(
                "protocol", f"PROTO-TAG-DUP:{value:#x}",
                f"frame tag value {value:#x} duplicated: {', '.join(names)}"))
    fence_base = tags.get("kTagShmSize")
    if fence_base is None:
        findings.append(Finding(
            "protocol", "PROTO-NO-FENCE-BASE",
            "kTagShmSize (the SockBarrier fence-metric threshold) not found"))
    else:
        for name, value in sorted(tags.items()):
            is_fence = name.startswith(("kTagShm", "kTagHier"))
            if is_fence and value < fence_base:
                findings.append(Finding(
                    "protocol", f"PROTO-TAG-RANGE:{name}",
                    f"{name}={value:#x} is a shm/hier fence tag below "
                    f"kTagShmSize={fence_base:#x}; SockBarrier would not "
                    f"count it as a fence"))
            if name == "kTagBarrier" and value >= fence_base:
                findings.append(Finding(
                    "protocol", f"PROTO-TAG-RANGE:{name}",
                    f"{name}={value:#x} (the user-visible barrier) sits in "
                    f"the >= {fence_base:#x} fence-metric range"))
    values = sorted(by_value)
    for lo, hi in zip(values, values[1:]):
        if hi - lo < 0x100:
            findings.append(Finding(
                "protocol", f"PROTO-TAG-SPACING:{hi:#x}",
                f"tags {', '.join(by_value[lo])} ({lo:#x}) and "
                f"{', '.join(by_value[hi])} ({hi:#x}) are {hi - lo} apart; "
                f"round/segment offsets need >= 0x100 of headroom"))

    # Wire-codec ids: wire_codec.h enum vs _core.py init map vs env.py names.
    cpp_codecs = parse_wire_codecs(wire_codec_text)
    py_codecs = parse_py_codec_map(core_py_text)
    if cpp_codecs != py_codecs:
        findings.append(Finding(
            "protocol", "PROTO-CODEC-MIRROR",
            f"wire codec ids disagree: wire_codec.h {cpp_codecs} vs "
            f"_core.py {py_codecs}"))
    em = re.search(r"WIRE_COMPRESSION_CODECS\s*=\s*\((.*?)\)", env_py_text,
                   re.S)
    env_names = re.findall(r'"(\w+)"', em.group(1)) if em else []
    want_order = [n for n, _ in sorted(cpp_codecs.items(),
                                       key=lambda kv: kv[1])]
    if env_names != want_order:
        findings.append(Finding(
            "protocol", "PROTO-CODEC-NAMES",
            f"env.py WIRE_COMPRESSION_CODECS {env_names} does not match the "
            f"id-ordered wire_codec.h names {want_order}"))

    # Device-plane mirror: ops/quantize.py reimplements the int8 block
    # codec as traced math, so its block geometry, codec-id map, and the
    # device-codec name list must track wire_codec.h / env.py exactly —
    # a drift here desyncs the in-jit ring from the byte-stream semantics.
    if quantize_py_text:
        for py_name, cpp_name in (("WIRE_BLOCK", "kWireBlock"),
                                  ("WIRE_SCALE_BYTES", "kWireScaleBytes"),
                                  ("WIRE_GROUP", "kWireGroup"),
                                  ("WIRE_INT4_MAX", "kWireInt4Max"),
                                  ("WIRE_SUB_DENOM", "kWireSubDenom")):
            qm = re.search(r"^%s\s*=\s*(\d+)" % py_name, quantize_py_text,
                           re.M)
            cm = re.search(r"constexpr\s+int64_t\s+%s\s*=\s*(\d+)" % cpp_name,
                           wire_codec_text)
            if not qm or not cm:
                findings.append(Finding(
                    "protocol", f"PROTO-QBLOCK-MISSING:{py_name}",
                    f"block-geometry constant missing: quantize.py "
                    f"{py_name} ({'found' if qm else 'absent'}) vs "
                    f"wire_codec.h {cpp_name} "
                    f"({'found' if cm else 'absent'})"))
            elif int(qm.group(1)) != int(cm.group(1)):
                findings.append(Finding(
                    "protocol", f"PROTO-QBLOCK:{py_name}",
                    f"quantize.py {py_name}={qm.group(1)} but wire_codec.h "
                    f"{cpp_name}={cm.group(1)}"))
        qi = re.search(r"^WIRE_CODEC_IDS\s*=\s*(\{[^}]*\})", quantize_py_text,
                       re.M)
        q_codecs = ({pm.group(1): int(pm.group(2)) for pm in
                     re.finditer(r'"(\w+)"\s*:\s*(\d+)', qi.group(1))}
                    if qi else {})
        if q_codecs != cpp_codecs:
            findings.append(Finding(
                "protocol", "PROTO-QCODEC-MIRROR",
                f"wire codec ids disagree: wire_codec.h {cpp_codecs} vs "
                f"quantize.py WIRE_CODEC_IDS {q_codecs}"))
        dm = re.search(r"^DEVICE_WIRE_CODECS\s*=\s*\((.*?)\)",
                       quantize_py_text, re.M | re.S)
        dev_names = re.findall(r'"(\w+)"', dm.group(1)) if dm else []
        edm = re.search(r"DEVICE_WIRE_COMPRESSION_CODECS\s*=\s*\((.*?)\)",
                        env_py_text, re.S)
        env_dev = re.findall(r'"(\w+)"', edm.group(1)) if edm else []
        if dev_names != env_dev:
            findings.append(Finding(
                "protocol", "PROTO-DEVICE-CODEC-NAMES",
                f"quantize.py DEVICE_WIRE_CODECS {dev_names} does not match "
                f"env.py DEVICE_WIRE_COMPRESSION_CODECS {env_dev}"))
        for name in dev_names:
            if name not in cpp_codecs:
                findings.append(Finding(
                    "protocol", f"PROTO-DEVICE-CODEC-UNKNOWN:{name}",
                    f"device codec {name!r} has no wire_codec.h enum id"))
    return findings


# ---------------------------------------------------------------------------
# flight-recorder event-type pass
# ---------------------------------------------------------------------------

# The doc table is located by this marker comment so the parser never
# confuses it with other numeric markdown tables (wire codecs, phases).
FLIGHT_DOC_MARKER = "<!-- hvd_lint:flight-types -->"


def parse_flight_enum(fr_h_text: str) -> Dict[int, str]:
    """{id: CamelSuffix} from flight_recorder.h's FlightType enum."""
    m = re.search(r"enum\s+FlightType[^{]*\{(.*?)\}", fr_h_text, re.S)
    if not m:
        return {}
    return {int(em.group(2)): em.group(1)
            for em in re.finditer(r"kFlight(\w+)\s*=\s*(\d+)", m.group(1))}


def parse_flight_legend(fr_cc_text: str) -> Dict[int, str]:
    """{id: snake_name} from flight_recorder.cc's kFlightTypesLegend."""
    m = re.search(r"kFlightTypesLegend\[\]\s*=(.*?);", fr_cc_text, re.S)
    if not m:
        return {}
    return {int(p.group(1)): p.group(2)
            for p in re.finditer(r'\\"(\d+)\\":\\"(\w+)\\"', m.group(1))}


def parse_flight_py(postmortem_text: str) -> Dict[int, str]:
    """{id: snake_name} from tools/postmortem.py's FLIGHT_TYPES."""
    m = re.search(r"FLIGHT_TYPES\s*=\s*\{(.*?)\}", postmortem_text, re.S)
    if not m:
        return {}
    return {int(p.group(1)): p.group(2)
            for p in re.finditer(r'(\d+)\s*:\s*"(\w+)"', m.group(1))}


def parse_flight_doc(doc_text: str) -> Optional[Dict[int, str]]:
    """{id: snake_name} from the marked table; None when no marker."""
    idx = doc_text.find(FLIGHT_DOC_MARKER)
    if idx < 0:
        return None
    # The table ends at the first blank line after the marker's table rows.
    rows: Dict[int, str] = {}
    for line in doc_text[idx:].splitlines()[1:]:
        if rows and not line.lstrip().startswith("|"):
            break
        rm = re.match(r"\s*\|\s*(\d+)\s*\|\s*`(\w+)`\s*\|", line)
        if rm:
            rows[int(rm.group(1))] = rm.group(2)
    return rows


def flight_pass(fr_h_text: str, fr_cc_text: str, postmortem_text: str,
                doc_files: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    enum = parse_flight_enum(fr_h_text)
    legend = parse_flight_legend(fr_cc_text)
    py_types = parse_flight_py(postmortem_text)
    for what, table, key in (("flight_recorder.h FlightType enum", enum,
                              "FLIGHT-NO-ENUM"),
                             ("flight_recorder.cc kFlightTypesLegend", legend,
                              "FLIGHT-NO-LEGEND"),
                             ("tools/postmortem.py FLIGHT_TYPES", py_types,
                              "FLIGHT-NO-PY")):
        if not table:
            findings.append(Finding(
                "flight", key, f"could not parse {what}"))
    if not (enum and legend and py_types):
        return findings

    if set(enum) != set(legend):
        findings.append(Finding(
            "flight", "FLIGHT-ENUM-LEGEND",
            f"FlightType enum ids {sorted(enum)} != kFlightTypesLegend ids "
            f"{sorted(legend)}"))
    else:
        for tid, camel in sorted(enum.items()):
            # Loose name check: the legend's snake name sans underscores and
            # the enum suffix must share a prefix (kFlightTreeAgg is the
            # abbreviation of tree_aggregate).
            a, b = camel.lower(), legend[tid].replace("_", "")
            if not (a.startswith(b) or b.startswith(a)):
                findings.append(Finding(
                    "flight", f"FLIGHT-NAME:{tid}",
                    f"type {tid}: enum kFlight{camel} does not match legend "
                    f"name {legend[tid]!r}"))
    if py_types != legend:
        findings.append(Finding(
            "flight", "FLIGHT-PY-MIRROR",
            f"tools/postmortem.py FLIGHT_TYPES {py_types} != "
            f"kFlightTypesLegend {legend}"))

    doc_rows = None
    doc_path = None
    for path, text in sorted(doc_files.items()):
        rows = parse_flight_doc(text)
        if rows is not None:
            doc_rows, doc_path = rows, path
            break
    if doc_rows is None:
        findings.append(Finding(
            "flight", "FLIGHT-DOC-NO-TABLE",
            f"no doc carries the {FLIGHT_DOC_MARKER} marked event-type "
            f"table"))
    else:
        for tid in sorted(set(legend) - set(doc_rows)):
            findings.append(Finding(
                "flight", f"FLIGHT-DOC-MISSING:{tid}",
                f"{doc_path}: event type {tid} ({legend[tid]}) missing from "
                f"the flight-types table"))
        for tid in sorted(set(doc_rows) - set(legend)):
            findings.append(Finding(
                "flight", f"FLIGHT-DOC-STALE:{tid}",
                f"{doc_path}: flight-types table row {tid} "
                f"({doc_rows[tid]}) names a type the C legend lacks"))
        for tid in sorted(set(doc_rows) & set(legend)):
            if doc_rows[tid] != legend[tid]:
                findings.append(Finding(
                    "flight", f"FLIGHT-DOC-RENAMED:{tid}",
                    f"{doc_path}: table calls type {tid} "
                    f"{doc_rows[tid]!r} but the legend says "
                    f"{legend[tid]!r}"))
    return findings


# ---------------------------------------------------------------------------
# Shared C++ mini-parser for the concurrency passes
#
# Pure text analysis, like every other pass: comments and string/char
# literals are blanked (length-preserving, so offsets stay line-accurate),
# then function bodies are located by brace matching.  The parser is
# deliberately scoped to this codebase's style (Google C++, no raw string
# literals, no preprocessor function definitions); it is not a general C++
# front end.
# ---------------------------------------------------------------------------

# `// lint: seq_cst-ok(<reason>)` / `// lint: sigsafe-ok(<reason>)` on the
# flagged line (or the line immediately above it) suppresses that site.
# Hatches are stale-checked like the env whitelists: one that no longer
# suppresses anything is itself a finding.
_HATCH_RE = re.compile(r"//\s*lint:\s*(seq_cst-ok|sigsafe-ok)\(([^)\n]*)\)")

_CPP_KEYWORDS = {
    "if", "for", "while", "switch", "catch", "do", "else", "return",
    "sizeof", "alignof", "decltype", "throw", "case", "default", "new",
    "delete", "static_cast", "reinterpret_cast", "const_cast",
    "dynamic_cast", "defined", "not", "and", "or", "assert",
    "static_assert", "typeid", "noexcept",
}


def strip_cpp(text: str) -> str:
    """Blank comments and string/char literals, preserving length/newlines."""
    out = list(text)
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            if j < 0:
                j = n
            for k in range(i, j):
                out[k] = " "
            i = j
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            for k in range(i, j):
                if out[k] != "\n":
                    out[k] = " "
            i = j
        elif c in "\"'":
            q = c
            j = i + 1
            while j < n and text[j] != q:
                j += 2 if text[j] == "\\" else 1
            for k in range(i + 1, min(j, n)):
                if out[k] != "\n":
                    out[k] = " "
            i = min(j, n) + 1
        else:
            i += 1
    return "".join(out)


def collect_hatches(raw_text: str) -> Dict[int, str]:
    """{1-based line: hatch kind} for every `// lint: *-ok(...)` comment."""
    hatches: Dict[int, str] = {}
    for lineno, line in enumerate(raw_text.splitlines(), 1):
        m = _HATCH_RE.search(line)
        if m:
            hatches[lineno] = m.group(1)
    return hatches


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def _match_brace(text: str, open_pos: int) -> int:
    """Index of the '}' matching the '{' at open_pos (len(text) if none)."""
    depth = 0
    for i in range(open_pos, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return i
    return len(text)


def _header_function_name(header: str) -> Optional[str]:
    """Function name if `header {` opens a function body, else None.

    Containers (namespace/struct/class/enum/extern blocks), control flow,
    brace initializers, and lambdas all return None.
    """
    header = header.strip()
    # Constructor member-initializer list: cut at the single ':' that sits
    # at paren depth 0 after the parameter list ("Foo::Foo(x) : a_(x)").
    depth = 0
    for i, ch in enumerate(header):
        if ch in "(<[":
            depth += 1
        elif ch in ")>]":
            depth = max(0, depth - 1)
        elif (ch == ":" and depth == 0 and header[i - 1:i] != ":"
              and header[i + 1:i + 2] != ":" and header[:i].rstrip().endswith(")")):
            header = header[:i]
            break
    # Strip trailing qualifiers so the header ends at the param list.
    while True:
        stripped = header.rstrip()
        for qual in ("const", "noexcept", "override", "final"):
            if stripped.endswith(qual):
                header = stripped[: -len(qual)]
                break
        else:
            break
    header = header.rstrip()
    if not header.endswith(")"):
        return None
    # Backward-match the parameter list's opening paren.
    depth = 0
    open_idx = -1
    for i in range(len(header) - 1, -1, -1):
        if header[i] == ")":
            depth += 1
        elif header[i] == "(":
            depth -= 1
            if depth == 0:
                open_idx = i
                break
    if open_idx <= 0:
        return None
    before = header[:open_idx].rstrip()
    if before.endswith("]"):  # lambda introducer
        return None
    m = re.search(r"([A-Za-z_~]\w*)$", before)
    if not m:
        return None
    name = m.group(1)
    if name in _CPP_KEYWORDS:
        return None
    return name


def parse_cpp_functions(stripped: str) -> List[Tuple[str, int, int]]:
    """[(name, body_open_idx, body_close_idx)] for every function definition.

    Containers (namespaces, classes, extern "C" blocks) are descended into;
    function bodies are consumed whole, so lambdas and control-flow braces
    inside them never register as functions of their own.
    """
    funcs: List[Tuple[str, int, int]] = []
    i, n = 0, len(stripped)
    last_stmt = 0
    paren = 0
    while i < n:
        c = stripped[i]
        if c == "(":
            paren += 1
        elif c == ")":
            paren = max(0, paren - 1)
        elif c == ";" and paren == 0:
            last_stmt = i + 1
        elif c == "}" and paren == 0:
            last_stmt = i + 1
        elif c == "{" and paren == 0:
            name = _header_function_name(stripped[last_stmt:i])
            if name is not None:
                end = _match_brace(stripped, i)
                funcs.append((name, i, end))
                i = end
                last_stmt = i + 1
            else:
                last_stmt = i + 1  # container or brace-init: descend
        i += 1
    return funcs


def _enclosing_function(funcs: Sequence[Tuple[str, int, int]],
                        pos: int) -> str:
    for name, start, end in funcs:
        if start <= pos <= end:
            return name
    return "<file scope>"


# ---------------------------------------------------------------------------
# atomic pass: explicit memory_order on every hot-path atomic op
# ---------------------------------------------------------------------------

# The always-on lock-free subsystems: every atomic op here runs on the
# negotiation/record hot path (or a crash path) where an accidental
# seq_cst fence is either a silent throughput tax or an unstated ordering
# claim.  Each op must name its memory_order so the required ordering is a
# reviewed decision, not a compiler default.
ATOMIC_HOT_FILES = {
    "metrics.cc", "metrics.h",
    "flight_recorder.cc", "flight_recorder.h",
    "step_trace.cc", "step_trace.h",
    "fleet_telemetry.cc", "fleet_telemetry.h",
    "fault_injection.cc", "fault_injection.h",
}

_ATOMIC_OP_RE = re.compile(
    r"\.\s*(load|store|exchange|fetch_add|fetch_sub|fetch_and|fetch_or|"
    r"fetch_xor|compare_exchange_strong|compare_exchange_weak)\s*\(")


def _balanced_args(stripped: str, open_pos: int) -> str:
    """The argument text of the call whose '(' is at open_pos."""
    depth = 0
    for i in range(open_pos, len(stripped)):
        if stripped[i] == "(":
            depth += 1
        elif stripped[i] == ")":
            depth -= 1
            if depth == 0:
                return stripped[open_pos + 1:i]
    return stripped[open_pos + 1:]


def atomic_pass(cc_files: Dict[str, str],
                hot_files: Optional[set] = None) -> List[Finding]:
    hot_files = ATOMIC_HOT_FILES if hot_files is None else hot_files
    findings: List[Finding] = []
    for path, raw in sorted(cc_files.items()):
        base = os.path.basename(path)
        if base not in hot_files:
            continue
        stripped = strip_cpp(raw)
        funcs = parse_cpp_functions(stripped)
        hatches = collect_hatches(raw)
        used_hatches: set = set()
        for m in _ATOMIC_OP_RE.finditer(stripped):
            op = m.group(1)
            args = _balanced_args(stripped, m.end() - 1)
            if "memory_order" in args:
                continue
            lineno = _line_of(stripped, m.start())
            hatch_line = next(
                (ln for ln in (lineno, lineno - 1)
                 if hatches.get(ln) == "seq_cst-ok"), None)
            if hatch_line is not None:
                used_hatches.add(hatch_line)
                continue
            expr = re.search(r"[\w\]\[.>-]*$",
                             stripped[:m.start()].split("\n")[-1])
            site = (expr.group(0) if expr and expr.group(0) else "<expr>")
            findings.append(Finding(
                "atomic", f"ATOMIC-IMPLICIT:{base}:{lineno}",
                f"{base}:{lineno} ({_enclosing_function(funcs, m.start())}): "
                f"{site}.{op}() names no memory_order — implicit seq_cst "
                f"is an unstated ordering claim (and a fence on the hot "
                f"path); spell the required order or annotate "
                f"`// lint: seq_cst-ok(<reason>)`"))
        for ln in sorted(set(ln for ln, kind in hatches.items()
                             if kind == "seq_cst-ok") - used_hatches):
            findings.append(Finding(
                "atomic", f"ATOMIC-STALE-OK:{base}:{ln}",
                f"{base}:{ln}: `lint: seq_cst-ok` hatch suppresses nothing "
                f"(no implicit-order atomic op on this or the next line) — "
                f"remove it"))
    return findings


# ---------------------------------------------------------------------------
# lockorder pass: inter-mutex acquisition graph, cycles = deadlock risk
# ---------------------------------------------------------------------------

# The files whose mutexes guard the coordinator / ABI / shm planes.  The
# analysis is per file: these mutexes are file-local, and internal calls
# in them are unqualified member/free calls (dotted calls go to other
# objects — sockets, maps — and are excluded from the call graph).
LOCKORDER_FILES = {"socket_controller.cc", "core_api.cc", "shm_plane.cc"}

_GUARD_RE = re.compile(
    r"\b(?:std::)?(?:lock_guard|unique_lock|scoped_lock)\s*<[^;(){}]*>\s*"
    r"\w+\s*\(\s*([^(),;{}]+?)\s*[,)]")

_CALL_RE = re.compile(r"(?<![\w.>:])([A-Za-z_]\w*)\s*\(")


def _mutex_name(expr: str) -> str:
    """'g->queue_mu' / 'S().init_mu' -> trailing identifier."""
    ids = re.findall(r"\w+", expr)
    return ids[-1] if ids else expr.strip()


def _function_lock_profile(stripped: str, name: str, body: Tuple[int, int],
                           local_funcs: set):
    """(direct_edges, held_calls, acquires, callees) for one function body.

    direct_edges: [(held_mutex, acquired_mutex, lineno)]
    held_calls:   [(held_mutexes_frozenset, callee, lineno)]
    acquires:     {mutex} acquired anywhere in the body
    callees:      {local function} called anywhere in the body
    """
    start, end = body
    text = stripped[start:end + 1]
    events = []  # (offset, kind, payload)
    for m in _GUARD_RE.finditer(text):
        events.append((m.start(), "guard", _mutex_name(m.group(1))))
    for m in _CALL_RE.finditer(text):
        callee = m.group(1)
        if callee in local_funcs and callee != name \
                and callee not in _CPP_KEYWORDS:
            events.append((m.start(), "call", callee))
    events.sort()
    direct_edges, held_calls = [], []
    acquires, callees = set(), set()
    held: List[Tuple[str, int]] = []  # (mutex, depth at declaration)
    depth = 0
    ei = 0
    for i, ch in enumerate(text):
        while ei < len(events) and events[ei][0] == i:
            _, kind, payload = events[ei]
            ei += 1
            lineno = _line_of(stripped, start + i)
            if kind == "guard":
                acquires.add(payload)
                for held_mu, _ in held:
                    direct_edges.append((held_mu, payload, lineno))
                held.append((payload, depth))
            else:
                callees.add(payload)
                if held:
                    held_calls.append(
                        (frozenset(mu for mu, _ in held), payload, lineno))
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            held = [(mu, d) for mu, d in held if d <= depth]
    return direct_edges, held_calls, acquires, callees


def lockorder_pass(cc_files: Dict[str, str],
                   files: Optional[set] = None) -> List[Finding]:
    files = LOCKORDER_FILES if files is None else files
    findings: List[Finding] = []
    for path, raw in sorted(cc_files.items()):
        base = os.path.basename(path)
        if base not in files:
            continue
        stripped = strip_cpp(raw)
        funcs = parse_cpp_functions(stripped)
        local_funcs = {name for name, _, _ in funcs}
        profiles = {}
        for name, fstart, fend in funcs:
            prof = _function_lock_profile(stripped, name, (fstart, fend),
                                          local_funcs)
            if name in profiles:  # overloads: union the profiles
                old = profiles[name]
                prof = (old[0] + prof[0], old[1] + prof[1],
                        old[2] | prof[2], old[3] | prof[3])
            profiles[name] = prof

        # Transitive closure: every mutex a function may acquire, itself
        # or via any intra-file callee.
        closure = {name: set(prof[2]) for name, prof in profiles.items()}
        changed = True
        while changed:
            changed = False
            for name, prof in profiles.items():
                for callee in prof[3]:
                    extra = closure.get(callee, set()) - closure[name]
                    if extra:
                        closure[name] |= extra
                        changed = True

        # Edge set with witnesses.
        edges: Dict[Tuple[str, str], List[str]] = {}
        for name, (direct_edges, held_calls, _, _) in profiles.items():
            for held_mu, acq_mu, lineno in direct_edges:
                edges.setdefault((held_mu, acq_mu), []).append(
                    f"{name} holds {held_mu}, acquires {acq_mu} "
                    f"({base}:{lineno})")
            for held_set, callee, lineno in held_calls:
                for acq_mu in closure.get(callee, ()):
                    for held_mu in held_set:
                        edges.setdefault((held_mu, acq_mu), []).append(
                            f"{name} holds {held_mu}, calls {callee} which "
                            f"may acquire {acq_mu} ({base}:{lineno})")

        # Self-deadlock: std::mutex is non-recursive, so A -> A is an
        # immediate hang on the first path that actually nests.
        for (a, b), wits in sorted(edges.items()):
            if a == b:
                findings.append(Finding(
                    "lockorder", f"LOCKORDER-SELF:{base}:{a}",
                    f"{base}: {a} may be acquired while already held "
                    f"(std::mutex is non-recursive): {wits[0]}"))

        # Cycles: Tarjan SCC, then one witness cycle per non-trivial SCC.
        adj: Dict[str, set] = {}
        for (a, b) in edges:
            if a != b:
                adj.setdefault(a, set()).add(b)
                adj.setdefault(b, set())
        for scc in _tarjan_sccs(adj):
            if len(scc) < 2:
                continue
            cycle = _witness_cycle(adj, scc)
            key_path = "->".join(cycle + [cycle[0]])
            wit_lines = []
            for x, y in zip(cycle, cycle[1:] + [cycle[0]]):
                wit_lines.append(edges[(x, y)][0])
            findings.append(Finding(
                "lockorder", f"LOCKORDER-CYCLE:{base}:{key_path}",
                f"{base}: lock-order cycle {key_path} — potential "
                f"deadlock; witness paths: " + "; ".join(wit_lines)))
    return findings


def _tarjan_sccs(adj: Dict[str, set]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: set = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                scc.append(w)
                if w == v:
                    break
            sccs.append(scc)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return sccs


def _witness_cycle(adj: Dict[str, set], scc: List[str]) -> List[str]:
    """One simple cycle through the SCC, starting at its min node."""
    scc_set = set(scc)
    start = min(scc)
    # BFS back to start restricted to the SCC.
    from collections import deque
    prev = {start: None}
    dq = deque([start])
    while dq:
        v = dq.popleft()
        for w in sorted(adj.get(v, ())):
            if w == start and v != start:
                path = [v]
                while prev[path[-1]] is not None:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            if w in scc_set and w not in prev:
                prev[w] = v
                dq.append(w)
    return [start]


# ---------------------------------------------------------------------------
# sigsafe pass: async-signal-safety of the fatal-signal dump path
# ---------------------------------------------------------------------------

# The file whose fatal-signal handlers this pass certifies.  Entry points
# are discovered from the handler-installation sites (`sa_handler = X`,
# `signal(SIG, X)`), so adding a handler automatically widens the audit.
SIGSAFE_FILE = "flight_recorder.cc"

_HANDLER_INSTALL_RE = re.compile(
    r"(?:\.sa_handler\s*=\s*|\bsignal\s*\(\s*\w+\s*,\s*)([A-Za-z_]\w*)")

# Callables permitted in a fatal-signal context: the POSIX
# async-signal-safe set this code actually uses, allocation-free string/
# memory primitives, and lock-free std::atomic member ops.  Everything
# else reachable from a handler is a finding.
SIGSAFE_ALLOWED_CALLS = {
    # POSIX async-signal-safe functions
    "write", "read", "open", "close", "rename", "unlink", "fsync",
    "raise", "kill", "_exit", "abort", "sigaction", "sigemptyset",
    "sigaddset", "signal", "clock_gettime", "time", "getpid",
    # allocation-free libc string/memory primitives
    "memcpy", "memmove", "memset", "strlen", "strncpy",
    # lock-free atomic member ops
    "load", "store", "exchange", "fetch_add", "fetch_sub", "fetch_and",
    "fetch_or", "fetch_xor", "compare_exchange_strong",
    "compare_exchange_weak",
    # constexpr header-inline helpers (no allocation, no locks, no errno)
    "min", "max",
}

# Tokens whose presence in a reachable body is an allocation or lock no
# matter how it is spelled as a call.
_SIGSAFE_NEW_RE = re.compile(r"\bnew\b")
_SIGSAFE_LOCK_RE = re.compile(
    r"\b(?:std::)?(?:lock_guard|unique_lock|scoped_lock)\b|\.\s*lock\s*\(")
_SIGSAFE_CALL_RE = re.compile(r"(?<![\w>])([A-Za-z_]\w*)\s*\(")


def sigsafe_pass(fr_cc_text: str,
                 filename: str = SIGSAFE_FILE) -> List[Finding]:
    findings: List[Finding] = []
    stripped = strip_cpp(fr_cc_text)
    hatches = collect_hatches(fr_cc_text)
    used_hatches: set = set()
    funcs = parse_cpp_functions(stripped)
    bodies: Dict[str, List[Tuple[int, int]]] = {}
    for name, start, end in funcs:
        bodies.setdefault(name, []).append((start, end))

    entries = sorted(set(_HANDLER_INSTALL_RE.findall(stripped))
                     & set(bodies))
    if not entries:
        findings.append(Finding(
            "sigsafe", f"SIGSAFE-NO-ENTRY:{filename}",
            f"{filename}: no fatal-signal handler installation found "
            f"(sa_handler = X / signal(SIG, X)) — the signal-dump "
            f"async-signal-safety claim has nothing to anchor to"))
        return findings

    def _body_calls(name: str) -> List[Tuple[str, int]]:
        out = []
        for start, end in bodies.get(name, ()):
            text = stripped[start:end + 1]
            for m in _SIGSAFE_CALL_RE.finditer(text):
                out.append((m.group(1), _line_of(stripped, start + m.start())))
        return out

    # Reachability over the intra-file call graph (dotted calls included:
    # SafeWriter-style local struct methods are called through a value).
    reachable: List[str] = []
    seen = set(entries)
    queue = list(entries)
    while queue:
        fn = queue.pop(0)
        reachable.append(fn)
        for callee, _ in _body_calls(fn):
            if callee in bodies and callee not in seen:
                seen.add(callee)
                queue.append(callee)

    def _excused(lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            if hatches.get(ln) == "sigsafe-ok":
                used_hatches.add(ln)
                return True
        return False

    for fn in reachable:
        for callee, lineno in _body_calls(fn):
            if callee in bodies or callee in SIGSAFE_ALLOWED_CALLS \
                    or callee in _CPP_KEYWORDS:
                continue
            if _excused(lineno):
                continue
            findings.append(Finding(
                "sigsafe", f"SIGSAFE-UNSAFE-CALL:{fn}:{callee}",
                f"{filename}:{lineno}: {fn} (reachable from fatal-signal "
                f"handler {'/'.join(entries)}) calls {callee}(), which is "
                f"not on the async-signal-safe allowlist"))
        for start, end in bodies.get(fn, ()):
            text = stripped[start:end + 1]
            for m in _SIGSAFE_NEW_RE.finditer(text):
                lineno = _line_of(stripped, start + m.start())
                if _excused(lineno):
                    continue
                findings.append(Finding(
                    "sigsafe", f"SIGSAFE-NEW:{fn}:{lineno}",
                    f"{filename}:{lineno}: {fn} (reachable from the "
                    f"fatal-signal handler) allocates with `new` — malloc "
                    f"is not async-signal-safe"))
            for m in _SIGSAFE_LOCK_RE.finditer(text):
                lineno = _line_of(stripped, start + m.start())
                if _excused(lineno):
                    continue
                findings.append(Finding(
                    "sigsafe", f"SIGSAFE-LOCK:{fn}:{lineno}",
                    f"{filename}:{lineno}: {fn} (reachable from the "
                    f"fatal-signal handler) takes a lock — a mutex held "
                    f"by the interrupted thread deadlocks the dump"))
    for ln in sorted(set(ln for ln, kind in hatches.items()
                         if kind == "sigsafe-ok") - used_hatches):
        findings.append(Finding(
            "sigsafe", f"SIGSAFE-STALE-OK:{filename}:{ln}",
            f"{filename}:{ln}: `lint: sigsafe-ok` hatch suppresses "
            f"nothing (no unsafe construct on this or the next line) — "
            f"remove it"))
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _read(relpath: str) -> str:
    with open(os.path.join(REPO, relpath), encoding="utf-8",
              errors="replace") as f:
        return f.read()


def _collect(root: str, subdir: str, exts: Sequence[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    base = os.path.join(root, subdir)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if any(fn.endswith(e) for e in exts):
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                with open(full, encoding="utf-8", errors="replace") as f:
                    out[rel] = f.read()
    return out


PASS_NAMES = ("abi", "env", "protocol", "flight", "atomic", "lockorder",
              "sigsafe")


def run_repo(root: str = REPO, only: Optional[Sequence[str]] = None,
             timings: Optional[Dict[str, float]] = None) -> List[Finding]:
    """Run the selected passes (all by default) over the repo at `root`.

    `only` narrows to a subset of PASS_NAMES; `timings`, when given, is
    filled with {pass_name: wall_seconds} for the passes that ran.
    """
    selected = set(PASS_NAMES) if only is None else set(only)
    unknown = selected - set(PASS_NAMES)
    if unknown:
        raise ValueError(f"unknown pass(es): {sorted(unknown)}; "
                         f"valid: {', '.join(PASS_NAMES)}")
    py_files = _collect(root, "horovod_tpu", (".py",))
    cc_files = _collect(root, os.path.join("horovod_tpu", "cpp"),
                        (".cc", ".h"))
    doc_files = _collect(root, "docs", (".md",))
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        with open(readme, encoding="utf-8") as f:
            doc_files["README.md"] = f.read()
    pm_path = os.path.join(root, "tools", "postmortem.py")
    pm_text = ""
    if os.path.exists(pm_path):
        with open(pm_path, encoding="utf-8", errors="replace") as f:
            pm_text = f.read()

    runners = {
        "abi": lambda: abi_pass(cc_files["horovod_tpu/cpp/core_api.cc"],
                                py_files),
        "env": lambda: env_pass(py_files, cc_files, doc_files),
        "protocol": lambda: protocol_pass(
            cc_files["horovod_tpu/cpp/socket_controller.cc"],
            cc_files["horovod_tpu/cpp/wire_codec.h"],
            py_files["horovod_tpu/_core.py"],
            py_files["horovod_tpu/runtime.py"],
            py_files["horovod_tpu/utils/env.py"],
            doc_files,
            quantize_py_text=py_files.get("horovod_tpu/ops/quantize.py",
                                          "")),
        "flight": lambda: flight_pass(
            cc_files["horovod_tpu/cpp/flight_recorder.h"],
            cc_files["horovod_tpu/cpp/flight_recorder.cc"],
            pm_text, doc_files),
        "atomic": lambda: atomic_pass(cc_files),
        "lockorder": lambda: lockorder_pass(cc_files),
        "sigsafe": lambda: sigsafe_pass(
            cc_files.get("horovod_tpu/cpp/" + SIGSAFE_FILE, "")),
    }
    findings: List[Finding] = []
    for pass_name in PASS_NAMES:
        if pass_name not in selected:
            continue
        t0 = time.perf_counter()
        findings += runners[pass_name]()
        if timings is not None:
            timings[pass_name] = time.perf_counter() - t0
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=REPO)
    ap.add_argument("--json", metavar="PATH",
                    help="also write the full machine-readable report here")
    ap.add_argument("--only", metavar="PASS[,PASS...]",
                    help="run only these passes (of: %s) — lets CI rows "
                    "run the cheap passes quickly and attribute slow ones"
                    % ", ".join(PASS_NAMES))
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "tools",
                                         "hvd_lint_baseline.json"))
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept all current findings as the new baseline")
    args = ap.parse_args(argv)

    only = None
    if args.only:
        only = [p.strip() for p in args.only.split(",") if p.strip()]
        try:
            run_names = [p for p in PASS_NAMES if p in set(only)]
            if set(only) - set(PASS_NAMES):
                raise ValueError
        except ValueError:
            ap.error(f"--only: unknown pass in {args.only!r}; valid: "
                     f"{', '.join(PASS_NAMES)}")
    else:
        run_names = list(PASS_NAMES)

    timings: Dict[str, float] = {}
    findings = run_repo(args.repo, only=only, timings=timings)
    baseline_keys: set = set()
    if os.path.exists(args.baseline):
        with open(args.baseline, encoding="utf-8") as f:
            baseline_keys = set(json.load(f).get("findings", []))
    new = [f for f in findings if f.key not in baseline_keys]

    for pass_name in run_names:
        hits = [f for f in findings if f.pass_name == pass_name]
        print(f"[{pass_name}] {len(hits)} finding(s) "
              f"({timings.get(pass_name, 0.0) * 1000:.1f} ms)")
        for f in hits:
            marker = " " if f.key in baseline_keys else "*"
            print(f"  {marker} {f.key}: {f.message}")
    print(f"hvd_lint: {len(findings)} finding(s), {len(new)} new vs baseline "
          f"({len(baseline_keys)} baselined)")

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump({"findings": [x.as_dict() for x in findings],
                       "new": [x.key for x in new]}, f, indent=2)
    if args.update_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump({"findings": sorted(x.key for x in findings)}, f,
                      indent=2)
        print(f"baseline updated: {args.baseline}")
        return 0
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
