"""Device-trace account of the ResNet-50 headline MFU (VERDICT r4 #3b).

Runs the same ResNet-50 train step bench.py measures, wrapped in
``hvd.start_device_trace`` (jax.profiler), then parses the captured
``*.xplane.pb`` with tensorboard_plugin_profile to attribute step time to
op categories (conv/fusion/copy/infeed/...), answering "where does the
other ~70% of the chip go" for the ~0.30 MFU figure.

Prints a JSON summary line starting with "RESULT ".  If the axon tunnel
does not forward device TraceMes, says so honestly (host-only planes).
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

import horovod_tpu as hvd
from horovod_tpu import models

LOGDIR = os.environ.get("MFU_TRACE_DIR", "/tmp/hvd_mfu_trace")
BATCH = int(os.environ.get("MFU_TRACE_BATCH", "256"))
STEPS = int(os.environ.get("MFU_TRACE_STEPS", "6"))


def build_step(mesh):
    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                            bn_axis_name="hvd")
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (BATCH, 224, 224, 3), jnp.bfloat16)
    labels = jnp.zeros((BATCH,), jnp.int32)
    variables = jax.jit(lambda: model.init(rng, images[:8], train=False))()
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                  axis_name="hvd")
    opt_state = tx.init(params)

    def train_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"])
            return models.xent_loss(logits, labels), updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, hvd.allreduce(loss,
                                                           axis_name="hvd")

    step = jax.jit(
        shard_map(train_step, mesh=mesh,
                  in_specs=(P(), P(), P(), P("hvd"), P("hvd")),
                  out_specs=(P(), P(), P(), P())),
        donate_argnums=(0, 1, 2))
    return step, params, batch_stats, opt_state, images, labels


def parse_xplane(logdir):
    """Pull per-op-category self-time out of the trace via the tensorboard
    profiler plugin's own converters."""
    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        return {"error": "no xplane.pb captured"}
    path = max(paths, key=os.path.getmtime)
    try:
        from tensorboard_plugin_profile.convert import raw_to_tool_data
    except Exception as exc:
        return {"error": f"tensorboard_plugin_profile unavailable: {exc}",
                "xplane": path}
    out = {"xplane": path}
    try:
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [path], "op_profile", {})
        out["op_profile"] = json.loads(data) if isinstance(data, str) else data
    except Exception as exc:
        out["op_profile_error"] = str(exc)[:300]
    try:
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [path], "overview_page", {})
        out["overview"] = json.loads(data) if isinstance(data, str) else data
    except Exception as exc:
        out["overview_error"] = str(exc)[:300]
    return out


def summarize_op_profile(op_profile):
    """Flatten the op_profile tree into (category -> fraction of total)."""
    try:
        root = op_profile["byCategory"]
        total = root["metrics"]["time"]
        cats = {}
        for child in root.get("children", []):
            t = child.get("metrics", {}).get("time", 0.0)
            cats[child.get("name", "?")] = round(t / max(total, 1e-9), 4)
        return dict(sorted(cats.items(), key=lambda kv: -kv[1]))
    except Exception as exc:
        return {"parse_error": str(exc)[:200]}


def main():
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("hvd",))
    hvd.init()
    step, params, batch_stats, opt_state, images, labels = build_step(mesh)
    # warmup/compile
    for _ in range(2):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    print(json.dumps({"phase": "warmup_done", "loss": float(loss)}),
          flush=True)

    os.makedirs(LOGDIR, exist_ok=True)
    hvd.start_device_trace(LOGDIR)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    float(loss)  # scalar readback bounds the enqueued work
    dt = time.perf_counter() - t0
    hvd.stop_device_trace()
    img_s = BATCH * STEPS / dt

    parsed = parse_xplane(LOGDIR)
    summary = {
        "img_per_sec_traced": round(img_s, 1),
        "step_ms_traced": round(dt / STEPS * 1e3, 2),
        "xplane": parsed.get("xplane"),
        "categories": summarize_op_profile(parsed.get("op_profile", {})),
    }
    for k in ("error", "op_profile_error", "overview_error"):
        if k in parsed:
            summary[k] = parsed[k]
    # The overview's device-time breakdown (infeed %, idle %) if present.
    try:
        ov = parsed["overview"]
        ia = ov.get("inputPipelineAnalysis", {})
        summary["infeed_pct"] = ia.get("infeedPercentAverage")
        gen = ov.get("generalAnalysis", {})
        summary["idle_ratio"] = gen.get("deviceIdleTimePercent")
        summary["mxu_util_pct"] = gen.get("mxuUtilizationPercent")
    except Exception:
        pass
    print("RESULT " + json.dumps(summary), flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
