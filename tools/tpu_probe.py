"""Standing TPU-tunnel probe (VERDICT r4 #3: "keep the standing probe").

Runs a tiny device-enumeration + matmul in a SUBPROCESS with a hard
timeout, so a wedged tunnel can never hang the caller.  Appends one JSON
line per probe to ``/tmp/tpu_probe.jsonl`` and exits 0 iff the chip both
enumerated AND executed a matmul.

The subprocess is the important part: libtpu is single-owner and a
half-dead tunnel answers ``jax.devices()`` but wedges on the first
executable load — both failure modes observed in rounds 2-5.  Holding
the chip in a long-lived prober would also starve the real work, so the
probe releases it immediately.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

PROBE_SRC = r"""
import time, json
t0 = time.time()
import jax
devs = jax.devices()
t_enum = time.time() - t0
import jax.numpy as jnp
y = (jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready()
t_exec = time.time() - t0
print("PROBE_OK " + json.dumps({
    "platform": devs[0].platform,
    "device_kind": getattr(devs[0], "device_kind", "?"),
    "enum_s": round(t_enum, 1),
    "exec_s": round(t_exec, 1),
}), flush=True)
"""


def probe(timeout_s: float = 240.0) -> dict:
    t0 = time.time()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # probe the real chip, not CPU
    try:
        out = subprocess.run(
            [sys.executable, "-u", "-c", PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s, env=env)
        ok_line = next((ln for ln in out.stdout.splitlines()
                        if ln.startswith("PROBE_OK ")), None)
        if ok_line and out.returncode == 0:
            rec = {"ok": True, **json.loads(ok_line[len("PROBE_OK "):])}
        else:
            tail = (out.stdout + out.stderr).strip().splitlines()[-3:]
            rec = {"ok": False, "rc": out.returncode, "tail": tail}
    except subprocess.TimeoutExpired:
        rec = {"ok": False, "rc": "timeout", "timeout_s": timeout_s}
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    rec["wall_s"] = round(time.time() - t0, 1)
    return rec


def main():
    timeout_s = float(sys.argv[1]) if len(sys.argv) > 1 else 240.0
    rec = probe(timeout_s)
    with open("/tmp/tpu_probe.jsonl", "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    sys.exit(0 if rec["ok"] else 1)


if __name__ == "__main__":
    main()
