#!/usr/bin/env python
"""Render compiled-collective inventories for the gspmd data plane.

The gspmd plane never builds a collective — XLA's SPMD partitioner
inserts them during compilation — so the only ground truth for "what
moved over the wire" is the compiled HLO module.  This tool renders that
inventory (horovod_tpu/ops/hlo_inspect.py) offline, from three sources:

  HLO text dumps      Positional args: optimized-module text files
                      (``compiled.as_text()`` saved to disk, or an
                      ``--xla_dump_to`` ``*.after_optimizations.txt``).
                      Each file is walked for compiler-inserted
                      collectives: kind, dtype, shape, replica-group
                      size, and analytic ring-model wire bytes.
  --bundle DIR        A crash bundle (tools/postmortem.py layout):
                      every type-16 ``hloinspect`` flight event is
                      tallied per rank (a = collective op count, b =
                      analytic wire bytes), so an aborted gspmd run
                      still reports what its traces inventoried.
  --live              Self-check: forces an 8-device CPU mesh, runs one
                      gspmd SGD step through ``hlo_inspect.instrument``,
                      and verifies the parsed inventory's analytic byte
                      totals match the live ``gspmd_byte_counters()``
                      exactly.  Exit code 1 on mismatch — CI-usable.

A ``--metrics FILE`` (a saved ``hvd.metrics()`` JSON dump) cross-checks
the analytic totals of the HLO inputs against the live
``gspmd_raw_bytes`` / ``gspmd_wire_bytes`` counters from the run that
produced the dump: exact match is the contract (both sides use the same
integer ring model), and a mismatch exits 1.

Usage:
    python tools/hlo_report.py module.after_optimizations.txt
    python tools/hlo_report.py dump1.txt dump2.txt --metrics metrics.json
    python tools/hlo_report.py --bundle /path/to/postmortem-dir
    python tools/hlo_report.py --live
    python tools/hlo_report.py ... --json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.ops import hlo_inspect  # noqa: E402

# Flight-recorder event type for hloinspect (the four synced copies:
# cpp/flight_recorder.h, its legend, tools/postmortem.py FLIGHT_TYPES,
# and the docs/observability.md table).
FLIGHT_HLO_INSPECT_TYPE = 16


# ---------------------------------------------------------------------------
# Source 1: HLO text dumps
# ---------------------------------------------------------------------------

def inventories_from_files(paths: List[str]) -> List[hlo_inspect.TraceInventory]:
    out = []
    for path in paths:
        with open(path) as f:
            text = f.read()
        out.append(hlo_inspect.inventory_from_text(
            text, label=os.path.basename(path)))
    return out


def render_inventory(inv: hlo_inspect.TraceInventory, out=sys.stdout) -> None:
    print(f"\ntrace {inv.label or '<unnamed>'}  "
          f"(num_partitions={inv.world})", file=out)
    print("-" * 72, file=out)
    if not inv.ops:
        print("  no compiler-inserted collectives", file=out)
        return
    print(f"  {'kind':<19} {'dtype':<9} {'elements':>9} {'g':>3} "
          f"{'raw_bytes':>10} {'wire_bytes':>10}  name", file=out)
    for op in inv.ops:
        mark = "*" if op.asynchronous else " "
        print(f"  {op.kind:<19} {op.dtype:<9} {op.elements:>9} "
              f"{op.group_size:>3} {op.raw_bytes:>10} {op.wire_bytes:>10} "
              f"{mark} {op.name}", file=out)
    kinds = ", ".join(f"{k}: {n}" for k, n in sorted(inv.kind_counts().items()))
    print(f"  total: {inv.collectives} collectives ({kinds}), "
          f"raw {inv.raw_bytes} B, analytic wire {inv.wire_bytes} B",
          file=out)
    if inv.cost:
        cost = ", ".join(f"{k}={v:g}" for k, v in sorted(inv.cost.items()))
        print(f"  compiler cost analysis: {cost}", file=out)


# ---------------------------------------------------------------------------
# Source 2: crash bundles (type-16 flight events)
# ---------------------------------------------------------------------------

def bundle_hlo_events(path: str) -> Dict[int, Dict[str, int]]:
    """Tally hloinspect flight events per rank from a postmortem bundle:
    digests in postmortem.json plus full flight.<rank>.json dumps (which
    supersede the digest for the same rank)."""
    if os.path.isdir(path):
        directory, pm_path = path, os.path.join(path, "postmortem.json")
    else:
        directory, pm_path = os.path.dirname(path) or ".", path
    per_rank_events: Dict[int, List[list]] = {}
    types: Dict[str, str] = {}
    if os.path.exists(pm_path):
        with open(pm_path) as f:
            pm = json.load(f)
        types = pm.get("types") or {}
        for rank_str, rec in (pm.get("ranks") or {}).items():
            per_rank_events[int(rank_str)] = rec.get("events") or []
    for fp in sorted(glob.glob(os.path.join(directory, "flight.*.json"))):
        m = re.match(r"flight\.(\d+)\.json$", os.path.basename(fp))
        if not m:
            continue
        try:
            with open(fp) as f:
                dump = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        types = types or dump.get("types") or {}
        per_rank_events[int(m.group(1))] = dump.get("events") or []

    def _is_hlo(typ: int) -> bool:
        name = types.get(str(typ))
        if name is not None:
            return name == "hloinspect"
        return typ == FLIGHT_HLO_INSPECT_TYPE

    tally: Dict[int, Dict[str, int]] = {}
    for rank, events in per_rank_events.items():
        rows = [e for e in events
                if isinstance(e, list) and len(e) >= 6 and _is_hlo(e[2])]
        if rows:
            tally[rank] = {"traces": len(rows),
                           "ops": sum(e[4] for e in rows),
                           "wire_bytes": sum(e[5] for e in rows)}
    return tally


def render_bundle(tally: Dict[int, Dict[str, int]], out=sys.stdout) -> None:
    print("\nhloinspect flight events (type 16) per rank", file=out)
    print("-" * 72, file=out)
    if not tally:
        print("  none recorded (eager-plane run, HOROVOD_HLO_INSPECT=0, "
              "or a pre-introspection .so)", file=out)
        return
    for rank in sorted(tally):
        t = tally[rank]
        print(f"  rank {rank:<4} traces={t['traces']:<4} "
              f"collectives={t['ops']:<6} "
              f"analytic wire bytes={t['wire_bytes']}", file=out)


# ---------------------------------------------------------------------------
# Cross-checks
# ---------------------------------------------------------------------------

def crosscheck_metrics(invs: List[hlo_inspect.TraceInventory],
                       metrics_path: str, out=sys.stdout) -> bool:
    """Compare the HLO inputs' analytic totals against the gspmd byte
    counters of a saved hvd.metrics() dump.  Exact equality is the bar:
    live counters and this tool share one integer wire model."""
    with open(metrics_path) as f:
        dump = json.load(f)
    counters = dump.get("counters") or {}
    live_raw = int(counters.get("gspmd_raw_bytes", 0))
    live_wire = int(counters.get("gspmd_wire_bytes", 0))
    raw = sum(i.raw_bytes for i in invs)
    wire = sum(i.wire_bytes for i in invs)
    ok = (raw == live_raw) and (wire == live_wire)
    print(f"\ncross-check vs {metrics_path}", file=out)
    print("-" * 72, file=out)
    print(f"  analytic (HLO inputs): raw {raw} B, wire {wire} B", file=out)
    print(f"  live counters        : raw {live_raw} B, wire {live_wire} B",
          file=out)
    print(f"  {'MATCH' if ok else 'MISMATCH'}", file=out)
    return ok


def live_check(devices: int = 8, out=sys.stdout) -> bool:
    """Run one gspmd SGD step on a forced multi-device CPU mesh through
    the instrumented path and verify inventory == live counters."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={devices}"
        ).strip()
    os.environ.pop("HOROVOD_HLO_INSPECT", None)  # the check needs it on

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.ops import gspmd_plane as gp
    from horovod_tpu.optimizer import DistributedOptimizer

    hlo_inspect.reset()
    mesh = gp.build_gspmd_mesh()
    rs = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(rs.randn(64, 4), jnp.float32),
                       NamedSharding(mesh, P(gp.BATCH_AXIS)))
    y = jax.device_put(jnp.asarray(rs.randn(64), jnp.float32),
                       NamedSharding(mesh, P(gp.BATCH_AXIS)))
    params = {"w": jnp.zeros((4,), jnp.float32),
              "b": jnp.zeros((), jnp.float32)}
    tx = DistributedOptimizer(optax.sgd(0.1), plane="gspmd")
    state = tx.init(params)

    def step(p, s, xs, ys):
        def loss(p):
            return jnp.mean((xs @ p["w"] + p["b"] - ys) ** 2)
        g = jax.grad(loss)(p)
        u, s2 = tx.update(g, s, p)
        return optax.apply_updates(p, u), s2

    wrapped = hlo_inspect.instrument(jax.jit(step), label="live_check")
    params, state = wrapped(params, state, x, y)
    jax.block_until_ready(params)

    invs = hlo_inspect.inventories()
    raw, wire = hlo_inspect.gspmd_byte_counters()
    for inv in invs:
        render_inventory(inv, out=out)
    a_raw = sum(i.raw_bytes for i in invs)
    a_wire = sum(i.wire_bytes for i in invs)
    ok = bool(invs) and invs[0].collectives > 0 \
        and a_raw == raw and a_wire == wire
    print(f"\nlive check: {len(invs)} trace(s), analytic raw/wire "
          f"{a_raw}/{a_wire} B vs counters {raw}/{wire} B -> "
          f"{'MATCH' if ok else 'MISMATCH'}", file=out)
    return ok


# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("hlo", nargs="*",
                   help="optimized HLO module text dumps to inventory")
    p.add_argument("--bundle", default=None, metavar="DIR",
                   help="postmortem bundle: tally type-16 flight events")
    p.add_argument("--metrics", default=None, metavar="FILE",
                   help="saved hvd.metrics() JSON: cross-check byte totals")
    p.add_argument("--live", action="store_true",
                   help="self-check on a forced multi-device CPU mesh")
    p.add_argument("--devices", type=int, default=8,
                   help="forced CPU device count for --live (default 8)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit machine-readable JSON instead of text")
    args = p.parse_args(argv)
    if not (args.hlo or args.bundle or args.live):
        p.error("nothing to do: give HLO dumps, --bundle, or --live")

    result: Dict[str, object] = {}
    ok = True
    invs = inventories_from_files(args.hlo) if args.hlo else []
    if invs:
        result["traces"] = [i.to_dict() for i in invs]
    if args.bundle:
        tally = bundle_hlo_events(args.bundle)
        result["bundle"] = {str(r): t for r, t in sorted(tally.items())}
    sink = sys.stderr if args.as_json else sys.stdout
    if args.live:
        ok = live_check(args.devices, out=sink) and ok
        result["live_ok"] = ok
    if not args.as_json:
        for inv in invs:
            render_inventory(inv)
        if args.bundle:
            render_bundle(bundle_hlo_events(args.bundle))
    if args.metrics:
        if not invs:
            print("--metrics needs HLO inputs to cross-check against",
                  file=sys.stderr)
            return 2
        match = crosscheck_metrics(invs, args.metrics, out=sink)
        result["metrics_match"] = match
        ok = match and ok
    if args.as_json:
        json.dump(result, sys.stdout, indent=2)
        print()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
