# Developer entry points.  The native core builds itself on first import
# (make -C horovod_tpu/cpp); these targets cover what CI runs.

lint:
	python tools/hvd_lint.py

selftest:
	$(MAKE) -C horovod_tpu/cpp selftest

test:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

clean:
	$(MAKE) -C horovod_tpu/cpp clean

.PHONY: lint selftest test clean
